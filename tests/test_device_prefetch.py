"""DevicePrefetcher coverage (data/device_prefetch.py): the async
input pipeline must yield committed ``NamedSharding`` batches over the
8-device conftest mesh, bound its read-ahead to the configured depth,
tear down cleanly on early abandon, propagate producer exceptions, pass
string keys through untouched — and preserve the wc-vid2vid first-window
crop-barrier ordering when stacked on a worker-threaded loader
(mirrors tests/test_person_crop_pipeline.py::TestFirstWindowBarrier at
prefetch depth > 1)."""

import threading
import time

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from imaginaire_tpu.data.device_prefetch import (
    DevicePrefetcher,
    PrefetchedBatch,
    prefetch_settings,
)
from imaginaire_tpu.parallel.mesh import create_mesh, peek_mesh, set_mesh


@pytest.fixture
def data_mesh():
    old = peek_mesh()
    mesh = create_mesh(("data",))
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(old)


def _batch(i, bs=8):
    rng = np.random.RandomState(i)
    return {
        "images": rng.rand(bs, 8, 8, 3).astype(np.float32),
        "label": rng.randint(0, 5, (bs, 8, 8)).astype(np.int32),
        "key": [f"item_{i}_{j}" for j in range(bs)],
        "nested": {"aux": rng.rand(bs, 2).astype(np.float32)},
    }


class _ListLoader:
    """Minimal loader: re-iterable, records how many batches were
    pulled (the producer's read-ahead)."""

    def __init__(self, batches, delay=0.0):
        self.batches = batches
        self.delay = delay
        self.pulled = 0

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        for b in self.batches:
            if self.delay:
                time.sleep(self.delay)
            self.pulled += 1
            yield dict(b) if isinstance(b, dict) else b


class TestShardingAndPassthrough:
    def test_committed_named_sharding_over_data_axis(self, data_mesh):
        pf = DevicePrefetcher(_ListLoader([_batch(0)]), depth=2)
        (out,) = list(pf)
        assert isinstance(out, PrefetchedBatch)
        for key in ("images", "label"):
            arr = out[key]
            assert isinstance(arr, jax.Array) and arr.committed
            assert isinstance(arr.sharding, NamedSharding)
            assert arr.sharding.spec == P(
                "data", *([None] * (arr.ndim - 1)))
            assert len(arr.sharding.mesh.devices.flat) == 8
        # nested numeric leaves get the same treatment
        assert out["nested"]["aux"].sharding.spec == P("data", None)

    def test_indivisible_batch_falls_back_uncommitted(self, data_mesh):
        """Nothing shards (3 % 8 != 0 on every leaf): the transfer keeps
        to_device's uncommitted placement instead of dragging the step
        program onto the full mesh for a replicated batch."""
        pf = DevicePrefetcher(_ListLoader([_batch(0, bs=3)]), depth=1)
        (out,) = list(pf)
        assert isinstance(out, PrefetchedBatch)
        assert isinstance(out["images"], jax.Array)
        assert not out["images"].committed

    def test_mixed_divisibility_replicates_odd_leaves(self, data_mesh):
        """Sharded main leaves carry replicated odd-sized siblings."""
        batch = dict(_batch(0), aux=np.zeros((3, 2), np.float32))
        pf = DevicePrefetcher(_ListLoader([batch]), depth=1)
        (out,) = list(pf)
        assert out["images"].sharding.spec == P("data", None, None, None)
        assert out["aux"].committed and out["aux"].sharding.spec == P()

    def test_string_keys_and_host_objects_pass_through(self, data_mesh):
        sentinel = object()
        batch = dict(_batch(1), _point_cloud=sentinel)
        pf = DevicePrefetcher(_ListLoader([batch]), depth=1)
        (out,) = list(pf)
        assert out["key"] == batch["key"]  # same host list, untouched
        assert out["_point_cloud"] is sentinel  # '_' host payloads kept
        assert not isinstance(out["key"], jax.Array)

    def test_host_preprocess_runs_with_pass_index(self, data_mesh):
        seen = []

        def prep(batch, index):
            seen.append(index)
            batch = dict(batch)
            batch["images"] = batch["images"] + 1.0
            return batch

        src = [_batch(i) for i in range(3)]
        pf = DevicePrefetcher(_ListLoader(src), host_preprocess=prep,
                              depth=2)
        outs = list(pf)
        assert seen == [0, 1, 2]
        np.testing.assert_allclose(np.asarray(outs[0]["images"]),
                                   src[0]["images"] + 1.0, rtol=1e-6)


class TestPipelineBehavior:
    def test_read_ahead_bounded_by_depth(self, data_mesh):
        loader = _ListLoader([_batch(i) for i in range(8)])
        pf = DevicePrefetcher(loader, depth=2)
        it = iter(pf)
        first = next(it)
        assert isinstance(first, PrefetchedBatch)
        # the producer may hold: 1 yielded + depth queued + 1 in flight
        deadline = time.time() + 2.0
        while loader.pulled < 2 and time.time() < deadline:
            time.sleep(0.01)  # overlap proof: read-ahead while we hold one
        assert 2 <= loader.pulled <= 1 + pf.depth + 1
        time.sleep(0.2)  # producer must stay blocked at the bound
        assert loader.pulled <= 1 + pf.depth + 1
        it.close()

    def test_early_abandon_unwinds_and_stays_reiterable(self, data_mesh):
        loader = _ListLoader([_batch(i) for i in range(16)])
        pf = DevicePrefetcher(loader, depth=2)
        for out in pf:  # abandon after the first batch (GeneratorExit)
            assert isinstance(out, PrefetchedBatch)
            break
        n_threads = threading.active_count()
        deadline = time.time() + 5.0
        while time.time() < deadline and any(
                t.name == "device-prefetch" and t.is_alive()
                for t in threading.enumerate()):
            time.sleep(0.01)
        assert not any(t.name == "device-prefetch" and t.is_alive()
                       for t in threading.enumerate()), \
            f"producer leaked ({n_threads} threads alive)"
        # a fresh pass over the same wrapper works (re-iterable contract)
        assert len(list(pf)) == 16

    def test_worker_exception_propagates(self, data_mesh):
        class Boom(RuntimeError):
            pass

        def bad_source():
            yield _batch(0)
            raise Boom("decode failed")

        class _GenLoader:
            def __iter__(self):
                return bad_source()

        pf = DevicePrefetcher(_GenLoader(), depth=2)
        with pytest.raises(Boom, match="decode failed"):
            list(pf)

    def test_preprocess_exception_propagates(self, data_mesh):
        pf = DevicePrefetcher(
            _ListLoader([_batch(0)]),
            host_preprocess=lambda b, i: (_ for _ in ()).throw(
                ValueError("hook failed")),
            depth=1)
        with pytest.raises(ValueError, match="hook failed"):
            list(pf)

    def test_stats_drain_without_device_sync(self, data_mesh):
        pf = DevicePrefetcher(_ListLoader([_batch(i) for i in range(3)]),
                              depth=2)
        list(pf)
        stats = pf.drain_stats()
        for name in ("data/host_wait_ms", "data/transfer_ms",
                     "data/queue_depth"):
            assert name in stats and len(stats[name]) >= 1
            assert all(isinstance(v, float) for v in stats[name])
        assert pf.drain_stats() == {}  # drained


class TestConfigKnob:
    def test_settings_default_bool_and_mapping(self):
        assert prefetch_settings({}) == (True, 2)
        assert prefetch_settings({"data": {"device_prefetch": False}}) \
            == (False, 2)
        assert prefetch_settings(
            {"data": {"device_prefetch": {"enabled": False}}}) == (False, 2)
        on, depth = prefetch_settings(
            {"data": {"device_prefetch": {"depth": 5}}})
        assert on and depth == 5

    def test_trainer_sync_path_when_off(self, data_mesh):
        """data.device_prefetch off: data_prefetcher is the identity and
        start_of_iteration keeps the synchronous to_device transfer."""
        from imaginaire_tpu.config import as_attrdict
        from imaginaire_tpu.trainers.base import BaseTrainer

        class Stub(BaseTrainer):
            def __init__(self, cfg):  # bypass net/optimizer construction
                self.cfg = as_attrdict(cfg)
                self.meters = {}
                self.current_iteration = 0

        trainer = Stub({"data": {"device_prefetch": {"enabled": False}},
                        "trainer": {}})
        loader = _ListLoader([_batch(0)])
        assert trainer.data_prefetcher(loader) is loader
        out = trainer.start_of_iteration(dict(_batch(0)), 0)
        assert isinstance(out["images"], jax.Array)
        assert out["key"][0] == "item_0_0"

    def test_trainer_wraps_and_skips_reprep_when_on(self, data_mesh):
        from imaginaire_tpu.config import as_attrdict
        from imaginaire_tpu.trainers.base import BaseTrainer

        calls = []

        class Stub(BaseTrainer):
            def __init__(self, cfg):
                self.cfg = as_attrdict(cfg)
                self.meters = {}
                self.current_iteration = 0

            def _start_of_iteration(self, data, current_iteration):
                calls.append(current_iteration)
                return data

        trainer = Stub({"data": {"device_prefetch": {"depth": 3}},
                        "trainer": {}})
        feed = trainer.data_prefetcher(
            _ListLoader([_batch(i) for i in range(2)]),
            iteration_of=lambda index: 100 + index)
        assert isinstance(feed, DevicePrefetcher) and feed.depth == 3
        outs = [trainer.start_of_iteration(d, 100 + i)
                for i, d in enumerate(feed)]
        # the hook ran once per batch, in the producer, with the
        # consuming iteration number — start_of_iteration didn't re-run it
        assert calls == [100, 101]
        assert all(isinstance(o, PrefetchedBatch) for o in outs)
        assert outs[0]["images"].committed
        trainer.write_data_meters(feed.drain_stats())
        assert "data/transfer_ms" in trainer.meters


class TestFirstWindowBarrierThroughPrefetch:
    def test_prefetch_depth2_preserves_frame0_bbox_sharing(self,
                                                           tmp_path,
                                                           data_mesh):
        """Stacking the device prefetcher (depth 2) on a worker-threaded
        loader must keep the wc/fs-vid2vid first-window barrier
        ordering: every frame of a pinned sequence uses frame 0's crop
        bbox even while the prefetcher pulls windows ahead (mirror of
        test_person_crop_pipeline.py::TestFirstWindowBarrier)."""
        import os

        cv2 = pytest.importorskip("cv2")

        from imaginaire_tpu.config import Config
        from imaginaire_tpu.data.loader import DataLoader
        from imaginaire_tpu.registry import resolve
        import imaginaire_tpu.model_utils.fs_vid2vid as fsu

        root = str(tmp_path / "raw")
        t = 8
        for dtype in ("images", "pose_maps-densepose"):
            os.makedirs(os.path.join(root, dtype, "seq0"), exist_ok=True)
        rng = np.random.RandomState(0)
        for i in range(t):
            img = rng.randint(0, 255, (96, 128, 3), np.uint8)
            cv2.imwrite(os.path.join(root, "images", "seq0",
                                     f"{i:05d}.jpg"), img)
            dp = np.zeros((96, 128, 3), np.uint8)
            dp[20 + 3 * i:60 + 3 * i, 30 + 4 * i:70 + 4 * i] = 120
            cv2.imwrite(os.path.join(root, "pose_maps-densepose", "seq0",
                                     f"{i:05d}.png"), dp)

        cfg = Config()
        cfg.data = {
            "name": "prefetch_barrier_test",
            "type": "imaginaire_tpu.data.paired_videos",
            "num_frames_G": 3, "num_frames_D": 3, "num_workers": 0,
            "for_pose_dataset": {"pose_type": "both",
                                 "remove_face_labels": False,
                                 "basic_points_only": False,
                                 "random_drop_prob": 0.0},
            "input_types": [
                {"images": {"ext": "jpg", "num_channels": 3,
                            "interpolator": "BILINEAR",
                            "normalize": True}},
                {"pose_maps-densepose": {"ext": "png", "num_channels": 3,
                                         "interpolator": "NEAREST",
                                         "normalize": False}},
            ],
            "full_data_ops": "imaginaire_tpu.model_utils."
                             "fs_vid2vid::crop_person_from_data",
            "input_image": ["images"],
            "input_labels": ["pose_maps-densepose"],
            "keypoint_data_types": [],
            "output_h_w": "64, 32",
            "train": {"roots": [root], "batch_size": 1,
                      "initial_sequence_length": 3,
                      "augmentations": {"resize_h_w": "96, 128",
                                        "horizontal_flip": False}},
            "val": {"roots": [root], "batch_size": 1,
                    "augmentations": {"resize_h_w": "96, 128",
                                      "horizontal_flip": False}},
        }

        used_coords = []
        orig = fsu.crop_person_from_data
        record_lock = threading.Lock()

        def recording(cfg_, is_inference, data, rng=None):
            dp0 = np.asarray(data["pose_maps-densepose"][0])
            if int(np.nonzero(dp0.sum((1, 2)))[0][0]) == 20:
                time.sleep(0.5)  # frame 0 slow: later frames must wait
            out = orig(cfg_, is_inference, data, rng=rng)
            with record_lock:
                used_coords.append(
                    tuple(out["common_attr"]["crop_coords"]))
            return out

        fsu.crop_person_from_data = recording
        try:
            ds = resolve(cfg.data.type, "Dataset")(cfg, is_inference=True)
            ds.set_inference_sequence_idx(0)
            loader = DataLoader(ds, batch_size=4, shuffle=False,
                                drop_last=False, num_workers=4,
                                prefetch_batches=2,
                                shard_by_process=False)
            pf = DevicePrefetcher(loader, depth=2)
            n = 0
            for out in pf:
                assert isinstance(out, PrefetchedBatch)
                assert isinstance(out["images"], jax.Array)
                n += 1
        finally:
            fsu.crop_person_from_data = orig
        assert n == 2 and len(used_coords) == t
        assert len(set(used_coords)) == 1, \
            f"every frame must reuse frame 0's bbox, got {set(used_coords)}"

"""Ring attention: exactness vs full attention on a virtual 8-device
mesh, and gradient flow through the ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from imaginaire_tpu.parallel.ring_attention import (
    ring_attention,
    ring_self_attention_2d,
)


def full_attention(q, k, v, scale=None):
    scale = scale or q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices()[:8])
    if devices.size < 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(devices, ("seq",))


class TestRingAttention:
    def test_matches_full_attention(self, mesh, rng):
        b, n, h, d = 2, 64, 4, 16  # 8 tokens per device
        q = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32))

        from imaginaire_tpu.parallel import shard_map

        ring = shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, "seq"),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"))
        got = jax.jit(ring)(q, k, v)
        want = full_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_gradients_flow_around_ring(self, mesh, rng):
        """d(output on device 0)/d(values on other devices) must be
        nonzero — the ring really attends across shards."""
        b, n, h, d = 1, 32, 2, 8
        q = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32))

        from imaginaire_tpu.parallel import shard_map

        ring = shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, "seq"),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"))

        def first_block_sum(v_):
            return jnp.sum(ring(q, k, v_)[:, :4])

        g = jax.jit(jax.grad(first_block_sum))(v)
        # values living on the LAST shard still influence the first block
        assert float(jnp.abs(g[:, -4:]).sum()) > 0

        want = jax.grad(
            lambda v_: jnp.sum(full_attention(q, k, v_)[:, :4]))(v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_spatial_wrapper(self, mesh, rng):
        b, h, w, c = 1, 16, 8, 32  # rows sharded: 2 rows per device
        x = jnp.asarray(rng.randn(b, h, w, c).astype(np.float32))

        from imaginaire_tpu.parallel import shard_map

        ring = shard_map(
            lambda x_: ring_self_attention_2d(x_, "seq", num_heads=4),
            mesh=mesh, in_specs=(P(None, "seq"),), out_specs=P(None, "seq"))
        got = jax.jit(ring)(x)
        tokens = x.reshape(b, h * w, 4, c // 4)
        want = full_attention(tokens, tokens, tokens).reshape(b, h, w, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_non_local_block_ring_mode(self, mesh, rng):
        """NonLocal2dBlock(ring_axis=..., ring_shard_map=False) runs
        inside an outer shard_map with rows sharded, using params
        initialized by the ring-free twin."""
        from imaginaire_tpu.parallel import shard_map

        from imaginaire_tpu.layers.non_local import NonLocal2dBlock

        x = jnp.asarray(rng.randn(1, 16, 8, 16).astype(np.float32))
        variables = NonLocal2dBlock().init(jax.random.PRNGKey(0), x)
        blk = NonLocal2dBlock(ring_axis="seq", ring_shard_map=False)
        with mesh:
            f = shard_map(lambda xx: blk.apply(variables, xx), mesh=mesh,
                          in_specs=(P(None, "seq"),),
                          out_specs=P(None, "seq"))
            out = jax.jit(f)(x)
        assert out.shape == x.shape
        assert np.all(np.isfinite(np.asarray(out)))

    def test_non_local_block_self_wrapping_island(self, rng):
        """The default ring_shard_map=True mode: the block wraps its own
        attention in a shard_map island over the process mesh, so it
        works from a stock jitted step (no outer shard_map)."""
        from imaginaire_tpu.layers.non_local import NonLocal2dBlock
        from imaginaire_tpu.parallel.mesh import create_mesh, get_mesh, set_mesh

        old = get_mesh()
        try:
            set_mesh(create_mesh(("data", "seq"), (2, 4)))
            x = jnp.asarray(rng.randn(1, 16, 8, 16).astype(np.float32))
            variables = NonLocal2dBlock().init(jax.random.PRNGKey(0), x)
            blk = NonLocal2dBlock(ring_axis="seq")
            out = jax.jit(lambda xx: blk.apply(variables, xx))(x)
            assert out.shape == x.shape
            assert np.all(np.isfinite(np.asarray(out)))
        finally:
            set_mesh(old)

    def test_non_local_ring_axis_missing_mesh_axis_raises(self, rng):
        from imaginaire_tpu.layers.non_local import NonLocal2dBlock
        from imaginaire_tpu.parallel.mesh import create_mesh, get_mesh, set_mesh

        old = get_mesh()
        try:
            set_mesh(create_mesh(("data",), (8,)))
            x = jnp.asarray(rng.randn(1, 8, 8, 16).astype(np.float32))
            variables = NonLocal2dBlock().init(jax.random.PRNGKey(0), x)
            blk = NonLocal2dBlock(ring_axis="seq")
            with pytest.raises(ValueError, match="ring_axis"):
                blk.apply(variables, x)
        finally:
            set_mesh(old)

    def test_non_local_ring_token_count_not_divisible_raises(self, rng):
        """A feature-map whose token count doesn't divide the ring axis
        must fail with an actionable message, not an opaque GSPMD
        error."""
        from imaginaire_tpu.layers.non_local import NonLocal2dBlock
        from imaginaire_tpu.parallel.mesh import create_mesh, get_mesh, set_mesh

        old = get_mesh()
        try:
            set_mesh(create_mesh(("data", "seq"), (2, 4)))
            # 5x5 = 25 tokens, not divisible by the seq axis size 4
            x = jnp.asarray(rng.randn(1, 5, 5, 16).astype(np.float32))
            variables = NonLocal2dBlock().init(jax.random.PRNGKey(0), x)
            blk = NonLocal2dBlock(ring_axis="seq")
            with pytest.raises(ValueError, match="divide"):
                blk.apply(variables, x)
        finally:
            set_mesh(old)


@pytest.mark.slow
class TestGeneratorRingAttention:
    def test_spade_training_step_with_ring_block(self, rng, tmp_path):
        """One real D+G training step through a SPADE generator whose
        non_local block runs ring attention over the 'seq' axis of a
        (2, 4) data x seq mesh — the config-reachable path
        (gen.non_local in configs/projects/spade/cocostuff/
        base128_bs4_attn.yaml)."""
        import os

        from imaginaire_tpu.config import Config
        from imaginaire_tpu.parallel.mesh import create_mesh, get_mesh, set_mesh
        from imaginaire_tpu.registry import resolve

        old = get_mesh()
        try:
            set_mesh(create_mesh(("data", "seq"), (2, 4)))
            cfg = Config(os.path.join(os.path.dirname(__file__), "..",
                                      "configs", "unit_test", "spade.yaml"))
            cfg.logdir = str(tmp_path)
            cfg.gen.non_local = {"enabled": True, "ring_axis": "seq"}
            trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
            batch = {
                "images": jnp.asarray(
                    rng.rand(2, 256, 256, 3).astype(np.float32) * 2 - 1),
                "label": jnp.asarray(
                    (rng.rand(2, 256, 256, 14) > 0.9).astype(np.float32)),
            }
            trainer.init_state(jax.random.PRNGKey(0), batch)
            b = trainer.start_of_iteration(batch, 1)
            d = trainer.dis_update(b)
            g = trainer.gen_update(b)
            for name, v in {**d, **g}.items():
                assert np.isfinite(float(jax.device_get(v))), name
            # the attention params exist and received a gradient step
            params = trainer.state["vars_G"]["params"]
            assert "non_local" in str(jax.tree_util.tree_structure(params))
        finally:
            set_mesh(old)

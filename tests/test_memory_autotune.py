"""memory_autotune pure core against a fake ledger (ISSUE 10): candidate
enumeration, pareto filtering, tie-breaking, and the budget refusal —
none of which should need an XLA compile to be trusted."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "scripts"))

import memory_autotune as ma  # noqa: E402


def _row(name, bs=1, temp=100, flops=10.0, footprint=None, **kw):
    return dict({"name": name, "batch_size": bs, "temp_bytes": temp,
                 "flops": flops,
                 "footprint_bytes": (footprint if footprint is not None
                                     else (temp or 0) + 50)}, **kw)


class TestEnumeration:
    def test_full_grid(self):
        cands = ma.enumerate_candidates(
            ["none", "blocks"], ["float32", "bfloat16"], [1, 4])
        assert len(cands) == 8
        assert cands[0] == {"name": "none/float32/bs1",
                            "remat_policy": "none",
                            "compute_dtype": "float32", "batch_size": 1}
        assert {c["name"] for c in cands} >= {"blocks/bfloat16/bs4",
                                              "none/bfloat16/bs1"}

    def test_policy_validated_by_shared_resolver(self):
        # same registry, same error message as the model-side knob
        with pytest.raises(ValueError, match="remat"):
            ma.enumerate_candidates(["block"], ["float32"], [1])

    def test_bad_dtype_and_bs_loud(self):
        with pytest.raises(ValueError, match="compute dtype"):
            ma.enumerate_candidates(["none"], ["float16"], [1])
        with pytest.raises(ValueError, match="batch size"):
            ma.enumerate_candidates(["none"], ["float32"], [0])

    def test_modulation_axis_opt_in(self):
        # ISSUE 16: the fused-SPADE axis doubles the grid and suffixes
        # candidate names; omitting it keeps the PR-9 name shape so old
        # MEMBENCH rows stay comparable
        plain = ma.enumerate_candidates(["none"], ["float32"], [4])
        assert [c["name"] for c in plain] == ["none/float32/bs4"]
        assert "spade_modulation" not in plain[0]
        both = ma.enumerate_candidates(["none"], ["float32"], [4],
                                       modulations=["fused", "unfused"])
        assert [c["name"] for c in both] \
            == ["none/float32/bs4/fused", "none/float32/bs4/unfused"]
        assert [c["spade_modulation"] for c in both] \
            == ["fused", "unfused"]

    def test_bad_modulation_loud(self):
        with pytest.raises(ValueError, match="modulation"):
            ma.enumerate_candidates(["none"], ["float32"], [1],
                                    modulations=["pallas"])


class TestFakeLedgerRows:
    def test_row_from_ledger_reduces_executables(self):
        cand = {"name": "blocks/bfloat16/bs4", "remat_policy": "blocks",
                "compute_dtype": "bfloat16", "batch_size": 4}
        row = ma.row_from_ledger(
            cand, "spade", (512, 512),
            {"gen_step": {"temp_bytes": 900, "total_bytes": 1500},
             "dis_step": {"temp_bytes": 400, "total_bytes": 700}},
            {"gen_step": 2e12, "dis_step": 1e12},
            state_bytes=300)
        assert row["temp_bytes"] == 900      # worst executable, not sum
        assert row["flops"] == 3e12          # dis + gen both run
        assert row["footprint_bytes"] == 1800  # worst total + state
        assert row["error"] is None
        assert row["family"] == "spade" and row["batch_size"] == 4

    def test_failed_compile_stays_unmeasured(self):
        cand = {"name": "none/float32/bs1", "remat_policy": "none",
                "compute_dtype": "float32", "batch_size": 1}
        row = ma.row_from_ledger(cand, "spade", (512, 512),
                                 {"gen_step": {}}, {}, state_bytes=0)
        assert row["temp_bytes"] is None and row["flops"] is None
        assert "failed" in row["error"]
        assert ma.pareto_frontier([row]) == []


class TestPareto:
    def test_dominated_rows_drop(self):
        rows = [_row("a", temp=100, flops=10.0),
                _row("b", temp=50, flops=20.0),
                _row("c", temp=120, flops=30.0),   # dominated by a
                _row("d", temp=80, flops=15.0)]
        assert [r["name"] for r in ma.pareto_frontier(rows)] \
            == ["b", "d", "a"]

    def test_exact_ties_both_survive(self):
        rows = [_row("a", temp=50, flops=10.0),
                _row("b", temp=50, flops=10.0)]
        assert [r["name"] for r in ma.pareto_frontier(rows)] == ["a", "b"]

    def test_unmeasured_never_on_frontier(self):
        rows = [_row("a", temp=None, flops=None),
                _row("b", temp=50, flops=10.0)]
        assert [r["name"] for r in ma.pareto_frontier(rows)] == ["b"]

    def test_legalized_rows_never_on_frontier(self):
        # ISSUE 16: a CPU-legalized bf16 row may look pareto-optimal but
        # measured a different program than the dtype it claims
        rows = [_row("bf16", temp=10, flops=1.0, legalized=True),
                _row("f32", temp=50, flops=10.0)]
        assert [r["name"] for r in ma.pareto_frontier(rows)] == ["f32"]


class TestRecommend:
    def test_bigger_batch_wins_over_smaller_temp(self):
        # the point of the autotuner: spend the savings as batch size
        rows = [_row("small-temp", bs=1, temp=10, flops=1.0),
                _row("big-batch", bs=4, temp=90, flops=9.0)]
        assert ma.recommend(rows)["name"] == "big-batch"

    def test_tie_breaks_temp_then_flops_then_name(self):
        rows = [_row("b", bs=2, temp=50, flops=5.0),
                _row("a", bs=2, temp=50, flops=5.0),
                _row("c", bs=2, temp=50, flops=4.0),
                _row("d", bs=2, temp=60, flops=1.0)]
        assert ma.recommend(rows)["name"] == "c"      # min flops at min temp
        rows = rows[:2]
        assert ma.recommend(rows)["name"] == "a"      # name order last

    def test_budget_filters_feasible_set(self):
        rows = [_row("fits", bs=1, temp=40, flops=9.0, footprint=80),
                _row("oom", bs=8, temp=10, flops=1.0, footprint=200)]
        # the bigger batch would win, but it doesn't fit the budget
        got = ma.recommend(rows, bytes_limit=100, mem_budget_frac=0.9)
        assert got["name"] == "fits"

    def test_refusal_when_nothing_fits(self):
        rows = [_row("a", footprint=200), _row("b", footprint=300)]
        with pytest.raises(ma.MemoryBudgetError, match="no candidate"):
            ma.recommend(rows, bytes_limit=100, mem_budget_frac=0.9)

    def test_refusal_when_nothing_measured(self):
        with pytest.raises(ma.MemoryBudgetError):
            ma.recommend([_row("a", temp=None, flops=None)])

    def test_no_limit_means_all_feasible(self):
        rows = [_row("huge", bs=4, footprint=10**15)]
        assert ma.recommend(rows, bytes_limit=None)["name"] == "huge"

    def test_legalized_rows_excluded_from_recommendation(self):
        rows = [_row("bf16-legal", bs=8, temp=10, flops=1.0,
                     legalized=True),
                _row("f32-real", bs=4, temp=90, flops=9.0)]
        assert ma.recommend(rows)["name"] == "f32-real"
        with pytest.raises(ma.MemoryBudgetError):
            ma.recommend([rows[0]])


class TestProfileRows:
    def test_winner_and_pareto_marked(self):
        rows = [_row("blocks/bfloat16/bs4", bs=4, temp=2**30, flops=1e12,
                     remat_policy="blocks", compute_dtype="bfloat16"),
                _row("none/float32/bs4", bs=4, temp=3 * 2**30, flops=9e11,
                     remat_policy="none", compute_dtype="float32")]
        lines = ma.profile_rows("spade", (512, 512), rows,
                                ["blocks/bfloat16/bs4", "none/float32/bs4"],
                                "blocks/bfloat16/bs4")
        assert any("**winner**" in ln and "blocks" in ln for ln in lines)
        assert all(ln.startswith("| spade 512x512 |") for ln in lines)

    def test_legalized_rows_marked_in_table(self):
        rows = [_row("none/bfloat16/bs1", bs=1, temp=2**30, flops=1e12,
                     remat_policy="none", compute_dtype="bfloat16",
                     legalized=True)]
        lines = ma.profile_rows("spade", (512, 512), rows, [], None)
        assert len(lines) == 1 and "legalized" in lines[0]

"""Test harness: force an 8-device virtual CPU mesh BEFORE jax import.

The reference has no multi-device tests at all (SURVEY.md section 4); we
test sharding logic for real by faking 8 host devices, which exercises
exactly the SPMD partitioning and collectives that run on a TPU slice.
"""

import os

# Force-override: the environment pre-sets JAX_PLATFORMS=axon (remote TPU
# tunnel), which makes every test compile over the wire. Unit tests always
# run on the virtual CPU mesh; bench.py uses the real chip.
#
# NOTE: sitecustomize.py (axon boot) imports jax at interpreter start, so
# setting os.environ here is too late for the env-var path — we must also
# set the config knob, which still works because backends aren't
# initialized until first use.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh, got " + str(jax.devices()))

jax.config.update("jax_threefry_partitionable", True)
# Persistent compilation cache: model-level tests compile big graphs;
# repeat runs hit the cache instead of recompiling.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)

"""Test harness: force an 8-device virtual CPU mesh BEFORE jax import.

The reference has no multi-device tests at all (SURVEY.md section 4); we
test sharding logic for real by faking 8 host devices, which exercises
exactly the SPMD partitioning and collectives that run on a TPU slice.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)

"""Telemetry stack coverage (ISSUE 2 satellite): JSONL sink round-trip,
span nesting/monotonicity, watchdog stack dumps, MFU math, Meter->sink
fan-out with TensorBoard parity, torch-free degradation, trace knob,
and the report renderer."""

import json
import logging
import os
import sys
import threading
import time

import pytest

from imaginaire_tpu import telemetry
from imaginaire_tpu.telemetry import core as tcore
from imaginaire_tpu.telemetry.report import (
    load_events,
    render_report,
    summarize,
)
from imaginaire_tpu.telemetry.sinks import JsonlSink, Sink


class CaptureSink(Sink):
    def __init__(self):
        self.events = []
        self.flushes = 0

    def emit(self, event):
        self.events.append(event)

    def flush(self):
        self.flushes += 1

    def of_kind(self, kind):
        return [e for e in self.events if e["kind"] == kind]


@pytest.fixture
def tm_sandbox():
    """Isolate the module singleton: each test configures its own
    Telemetry and the previous one is restored afterwards."""
    old = tcore._TELEMETRY
    yield
    tcore._TELEMETRY.shutdown()
    tcore._TELEMETRY = old


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_jsonl_sink_roundtrip(tm_sandbox, tmp_path):
    tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                             sinks=["jsonl"], flush_every_n_steps=0)
    with tm.span("gen_step", step=7):
        pass
    tm.counter("loss/total", 1.25, step=7)
    tm.meta("run_info", config="x.yaml")
    tm.shutdown()

    events = _read_jsonl(str(tmp_path / "telemetry.jsonl"))
    kinds = {e["kind"] for e in events}
    assert {"span", "counter", "meta"} <= kinds
    span = next(e for e in events if e["kind"] == "span")
    assert span["name"] == "gen_step" and span["step"] == 7
    assert span["dur_ms"] >= 0 and span["thread"]
    counter = next(e for e in events if e["kind"] == "counter")
    assert counter["name"] == "loss/total"
    assert counter["value"] == 1.25 and counter["step"] == 7


def test_span_nesting_and_timing_monotonicity(tm_sandbox):
    sink = CaptureSink()
    tm = telemetry.configure(enabled=True, sinks=[sink],
                             flush_every_n_steps=0)
    with tm.span("outer", step=1):
        time.sleep(0.002)
        with tm.span("inner", step=1):
            time.sleep(0.002)
        time.sleep(0.002)
    tm.flush()

    spans = {e["name"]: e for e in sink.of_kind("span")}
    assert spans["inner"]["parent"] == "outer"
    assert spans["outer"]["parent"] is None
    # the child closed first but started later; both clocks monotone
    assert spans["inner"]["t"] >= spans["outer"]["t"]
    assert spans["inner"]["dur_ms"] <= spans["outer"]["dur_ms"]
    assert spans["outer"]["dur_ms"] >= 6.0 - 1.0  # 3 sleeps, coarse clock


def test_same_name_nested_span_not_double_counted(tm_sandbox):
    tm = telemetry.configure(enabled=True, sinks=[],
                             flush_every_n_steps=0)
    with tm.span("data_wait"):
        with tm.span("data_wait"):
            time.sleep(0.001)
    phases = tm.window_summary()["phases"]
    assert phases["data_wait"]["count"] == 1


def test_disabled_singleton_is_noop(tmp_path):
    tm = tcore.Telemetry(enabled=False)
    with tm.span("x"):
        pass
    tm.counter("y", 1.0)
    tm.step_complete(0, items=4)
    tm.flush()
    assert tm.window_summary()["phases"] == {}


def test_watchdog_dumps_producer_thread_stack(tm_sandbox, tmp_path):
    release = threading.Event()

    def stalled_producer():
        release.wait(timeout=30)  # parked, like a blocked queue.get

    producer = threading.Thread(target=stalled_producer, daemon=True,
                                name="device-prefetch")
    producer.start()
    tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                             sinks=["jsonl"], flush_every_n_steps=0,
                             hang_timeout_s=0.15)
    tm.step_complete(1, items=1)  # arm the heartbeat
    deadline = time.time() + 10
    path = str(tmp_path / "telemetry.jsonl")
    hangs = []
    while time.time() < deadline and not hangs:
        time.sleep(0.05)
        if os.path.exists(path):
            hangs = [e for e in _read_jsonl(path) if e["kind"] == "hang"]
    release.set()
    producer.join(timeout=5)
    assert hangs, "watchdog never fired on a stalled step"
    hang = hangs[0]
    assert hang["step"] == 1
    assert "no step completed" in hang["reason"]
    assert "device-prefetch" in hang["stacks"], sorted(hang["stacks"])
    assert any("stalled_producer" in frame
               for frame in hang["stacks"]["device-prefetch"])
    # one dump per stall, not one per poll tick
    time.sleep(0.4)
    hangs = [e for e in _read_jsonl(path) if e["kind"] == "hang"]
    assert len(hangs) == 1


def test_watchdog_suspended_during_eval_span(tm_sandbox, tmp_path):
    """ISSUE 3 satellite: a long FID/KID sweep (an open ``eval`` span)
    must not read as a hang — and the stall clock re-arms when the span
    exits, so the watchdog stays live for real post-eval stalls."""
    tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                             sinks=["jsonl"], flush_every_n_steps=0,
                             hang_timeout_s=0.15)
    tm.step_complete(1, items=1)
    path = str(tmp_path / "telemetry.jsonl")
    with tm.span("eval", step=1):
        assert tm.watchdog_suspended()
        time.sleep(0.6)  # 4x the timeout, all inside the eval span
    assert not tm.watchdog_suspended()
    time.sleep(0.05)
    tm._push_to_sinks()
    hangs = [e for e in _read_jsonl(path)] if os.path.exists(path) else []
    assert not [e for e in hangs if e["kind"] == "hang"], \
        "watchdog fired during an eval span"
    # exiting the span re-armed the clock from NOW: a real stall after
    # eval still fires
    deadline = time.time() + 10
    fired = []
    while time.time() < deadline and not fired:
        time.sleep(0.05)
        if os.path.exists(path):
            fired = [e for e in _read_jsonl(path) if e["kind"] == "hang"]
    assert fired, "watchdog armed-after-eval never fired on a real stall"


def test_mfu_counter_matches_hand_computed_value(tm_sandbox):
    sink = CaptureSink()
    tm = telemetry.configure(enabled=True, sinks=[sink],
                             flush_every_n_steps=0, peak_flops=1e12)
    tm.set_step_flops(2e9)

    fake_now = [100.0]
    tm._clock = lambda: fake_now[0]
    tm.reset_window()
    for i in range(5):
        fake_now[0] += 0.01
        tm.step_complete(i, items=4, dur_s=0.01)
    tm.flush(step=4)

    counters = {e["name"]: e["value"] for e in sink.of_kind("counter")}
    # 5 steps of 2 GFLOP in 0.05 s against a 1 TFLOP/s peak => 20% MFU
    assert counters["perf/mfu"] == pytest.approx(0.2)
    assert counters["perf/imgs_per_sec"] == pytest.approx(400.0)
    assert counters["perf/step_time_ms_p50"] == pytest.approx(10.0)
    assert counters["perf/step_time_ms_p99"] == pytest.approx(10.0)
    meta = next(e for e in sink.of_kind("meta")
                if e["name"] == "step_flops")
    assert meta["flops"] == 2e9
    assert meta["peak_source"] == "config:telemetry.peak_flops"


def test_meter_fanout_keeps_tensorboard_parity(tm_sandbox, tmp_path,
                                               monkeypatch):
    from imaginaire_tpu.utils import meters

    class StubWriter:
        def __init__(self):
            self.scalars = []

        def add_scalar(self, name, value, step):
            self.scalars.append((name, float(value), step))

        def flush(self):
            pass

    stub = StubWriter()
    monkeypatch.setattr(meters, "_WRITER", stub)
    telemetry.configure(logdir=str(tmp_path), enabled=True,
                        sinks=["jsonl", "tensorboard"],
                        flush_every_n_steps=0)

    meter = meters.Meter("data/host_wait_ms")
    meter.write(2.0)
    meter.write(4.0)
    meter.flush(step=11)
    telemetry.get().shutdown()

    # TB got the averaged scalar exactly once (via the sink, not the
    # direct writer path on top of it). The xla_obs ledger may add its
    # own xla/* / mem/* counters on the flush cadence — those are not
    # meter fanout and are filtered from the parity check.
    meter_scalars = [s for s in stub.scalars
                     if not s[0].startswith(("xla/", "mem/"))]
    assert meter_scalars == [("data/host_wait_ms", 3.0, 11)]
    events = _read_jsonl(str(tmp_path / "telemetry.jsonl"))
    counter = next(e for e in events if e["kind"] == "counter"
                   and e["name"] == "data/host_wait_ms")
    assert counter["value"] == 3.0 and counter["step"] == 11


def test_meter_nonfinite_warns_and_counts(tm_sandbox, tmp_path, caplog):
    from imaginaire_tpu.utils import meters

    telemetry.configure(logdir=str(tmp_path), enabled=True,
                        sinks=["jsonl"], flush_every_n_steps=0)
    meter = meters.Meter("gen_update/total")
    meter.write(1.0)
    meter.write(float("nan"))
    meter.write(float("inf"))
    with caplog.at_level(logging.WARNING,
                         logger="imaginaire_tpu.utils.meters"):
        meter.flush(step=3)
    telemetry.get().shutdown()

    assert any("non-finite" in rec.message for rec in caplog.records)
    events = _read_jsonl(str(tmp_path / "telemetry.jsonl"))
    counters = {e["name"]: e["value"] for e in events
                if e["kind"] == "counter"}
    assert counters["gen_update/total/nonfinite_count"] == 2.0
    assert counters["gen_update/total"] == 1.0  # finite mean still lands


def test_set_summary_writer_degrades_without_torch(tmp_path, monkeypatch):
    from imaginaire_tpu.utils import meters

    monkeypatch.setattr(meters, "_WRITER", None)
    # None in sys.modules makes `import torch.utils.tensorboard` raise
    # ImportError — the torch-free-host simulation
    monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", None)
    meters.set_summary_writer(str(tmp_path))  # must not raise
    assert meters.get_summary_writer() is None
    # and the writer-less write path stays a no-op, not a crash
    meters.write_summary("x", 1.0, 0)


def test_trace_at_step_knob(tm_sandbox, monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda path: calls.append(("start", path)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    tm = telemetry.configure(enabled=True, sinks=[], logdir="/tmp/x",
                             flush_every_n_steps=0, trace_at_step=3,
                             trace_num_steps=2)
    for step in range(1, 7):
        tm.step_complete(step)
    assert [c[0] for c in calls] == ["start", "stop"]
    assert calls[0][1].endswith("/trace")
    # started exactly at step 3, stopped once step 3+2 was reached
    spans = [e for e in tm._events if e["kind"] == "meta"]
    steps = {e["name"]: e["step"] for e in spans}
    assert steps["trace_started"] == 3
    assert steps["trace_stopped"] == 5


def test_window_summary_data_wait_share(tm_sandbox):
    tm = telemetry.configure(enabled=True, sinks=[],
                             flush_every_n_steps=0)
    fake_now = [10.0]
    tm._clock = lambda: fake_now[0]
    tm.reset_window()
    with tm.span("data_wait"):
        time.sleep(0.01)
    fake_now[0] += 0.1
    tm.step_complete(0, items=2)
    s = tm.window_summary()
    assert s["duration_s"] == pytest.approx(0.1)
    assert 5.0 < s["data_wait_share_pct"] < 50.0
    assert s["imgs_per_sec"] == pytest.approx(20.0)


def test_report_renders_phase_table(tm_sandbox, tmp_path):
    tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                             sinks=["jsonl"], flush_every_n_steps=0)
    for step in range(3):
        with tm.span("dis_step", step=step):
            time.sleep(0.001)
        with tm.span("gen_step", step=step):
            time.sleep(0.002)
        tm.step_complete(step, items=2, dur_s=0.003)
    tm.flush(step=2)
    tm.shutdown()

    path = str(tmp_path / "telemetry.jsonl")
    report = render_report(path)
    assert "| gen_step | 3 |" in report
    assert "| dis_step | 3 |" in report
    assert "perf/imgs_per_sec" in report
    summary = summarize(load_events(path))
    assert summary["phases"]["gen_step"]["count"] == 3
    assert not summary["hangs"]


def test_telemetry_report_cli(tm_sandbox, tmp_path):
    import subprocess

    tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                             sinks=["jsonl"], flush_every_n_steps=0)
    with tm.span("ckpt", step=1):
        pass
    tm.shutdown()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts",
                                      "telemetry_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ckpt" in r.stdout


def _tiny_trainer(logdir):
    """Smallest real BaseTrainer loop (two Dense-net step programs):
    fast to compile, exercises the full instrumented iteration surface
    including the one-time cost-analysis MFU registration."""
    import jax.numpy as jnp
    from flax import linen as nn

    from imaginaire_tpu.config import Config
    from imaginaire_tpu.trainers.base import BaseTrainer

    class TinyG(nn.Module):
        @nn.compact
        def __call__(self, data, training=False):
            return {"fake_images": nn.Dense(3)(data["images"])}

    class TinyD(nn.Module):
        @nn.compact
        def __call__(self, data, net_G_output, training=False):
            dense = nn.Dense(1)
            return {"real_outputs": [dense(data["images"])],
                    "fake_outputs": [dense(net_G_output["fake_images"])]}

    class TinyTrainer(BaseTrainer):
        def _init_loss(self, cfg):
            self.weights = {"l2": 1.0}

        def gen_forward(self, vars_G, vars_D, loss_params, data, rng,
                        training=True):
            out = self.net_G.apply(vars_G, data, training=training)
            return {"l2": jnp.mean(out["fake_images"] ** 2)}, {}

        def dis_forward(self, vars_G, vars_D, loss_params, data, rng,
                        training=True):
            out = self.net_G.apply(vars_G, data, training=training)
            d_out = self.net_D.apply(vars_D, data, out,
                                     training=training)
            return {"l2": jnp.mean(d_out["real_outputs"][0] ** 2)
                    + jnp.mean(d_out["fake_outputs"][0] ** 2)}, {}

    cfg = Config()
    cfg.logdir = logdir
    return TinyTrainer(cfg, net_G=TinyG(), net_D=TinyD())


def test_trainer_step_emits_spans_counters_and_mfu(tm_sandbox, tmp_path):
    """End-to-end: a real BaseTrainer loop emits data_wait/dis_step/
    gen_step spans, throughput counters, and the cost-analysis MFU."""
    import jax
    import numpy as np

    trainer = _tiny_trainer(str(tmp_path))
    rng = np.random.RandomState(0)
    batch = {"images": rng.rand(2, 8, 3).astype(np.float32)}

    tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                             sinks=["jsonl"], flush_every_n_steps=2)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    for i in range(3):
        data = trainer.start_of_iteration(batch, i)
        trainer.dis_update(data)
        trainer.gen_update(data)
        trainer.end_of_iteration(data, 0, i + 1)
    tm.shutdown()

    events = _read_jsonl(str(tmp_path / "telemetry.jsonl"))
    names = {e["name"] for e in events if e["kind"] == "span"}
    # no cost_analysis span anymore: the compile ledger (xla_obs)
    # records FLOPs from the same compile that runs the step
    assert {"data_wait", "dis_step", "gen_step"} <= names
    counters = {e["name"] for e in events if e["kind"] == "counter"}
    assert "perf/imgs_per_sec" in counters
    assert "perf/mfu" in counters  # XLA cost analysis worked on CPU
    assert any(c.startswith("xla/compile/gen_step/") for c in counters)
    spans = [e for e in events if e["kind"] == "span"
             and e["name"] == "gen_step"]
    assert len(spans) == 3
    meta = next(e for e in events if e["kind"] == "meta"
                and e["name"] == "step_flops")
    assert meta["flops"] > 0


def test_span_overhead_stays_negligible(tm_sandbox):
    """The per-span cost (enabled, buffering) must stay micro-scale —
    the <1% step-overhead acceptance budget at ms-scale steps."""
    tm = telemetry.configure(enabled=True, sinks=[],
                             flush_every_n_steps=0, ring_size=64)
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        with tm.span("gen_step", step=i):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 200e-6, f"span overhead {per_span * 1e6:.1f}us"

import io

from imaginaire_tpu.config import AttrDict, Config, cfg_get, load_yaml, recursive_update


def test_attrdict_basic():
    d = AttrDict({"a": 1, "b": {"c": 2}})
    assert d.a == 1
    assert d.b.c == 2
    d.b.e = {"f": 3}
    assert d.b.e.f == 3
    assert isinstance(d.to_dict()["b"], dict)


def test_recursive_update():
    base = AttrDict({"a": {"x": 1, "y": 2}, "b": 3})
    recursive_update(base, {"a": {"y": 5}, "c": [1, 2]})
    assert base.a.x == 1 and base.a.y == 5 and base.b == 3
    assert base.c == [1, 2]


def test_float_resolver():
    # YAML 1.1 would parse 1e-4 as a string; our loader must yield float
    # (ref: imaginaire/config.py:154-164).
    cfg = load_yaml(io.StringIO("lr: 1e-4\nother: 2.5e3\nname: e5\n"))
    assert isinstance(cfg["lr"], float) and abs(cfg["lr"] - 1e-4) < 1e-12
    assert isinstance(cfg["other"], float)
    assert cfg["name"] == "e5"


def test_config_defaults_and_overlay(tmp_path):
    p = tmp_path / "exp.yaml"
    p.write_text(
        "max_iter: 7\n"
        "gen:\n  type: imaginaire_tpu.models.generators.spade\n  num_filters: 32\n"
        "common:\n  shared_flag: true\n"
    )
    cfg = Config(str(p))
    assert cfg.max_iter == 7
    assert cfg.max_epoch == 200  # default preserved
    assert cfg.gen.num_filters == 32
    # common broadcast into gen and dis (ref: config.py:173-177)
    assert cfg.gen.shared_flag is True
    assert cfg.dis.shared_flag is True
    assert cfg_get(cfg.gen, "missing", 11) == 11


def test_registry_reference_alias():
    from imaginaire_tpu.registry import _translate_reference_name

    assert (
        _translate_reference_name("imaginaire.generators.spade")
        == "imaginaire_tpu.models.generators.spade"
    )

"""fp32 islands under the bf16 compute policy (ISSUE 10): the params
cast is surgical — norm statistics, spectral-norm power iteration, and
health-audit norms stay float32, and the runtime asserts refuse a bf16
leak instead of silently degrading."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from imaginaire_tpu.config import Config
from imaginaire_tpu.layers.activation_norm import InstanceNorm, LayerNorm2d
from imaginaire_tpu.layers.weight_norm import (
    estimate_sigma,
    power_iteration,
    spectral_normalize,
)
from imaginaire_tpu.trainers.base import BaseTrainer

import os

CFG_PATH = os.path.join(os.path.dirname(__file__), "..", "configs",
                        "unit_test", "spade.yaml")


class _Caster:
    """BaseTrainer's cast helpers without the ctor: the methods only
    touch ``self.compute_dtype``."""

    _to_compute_dtype = BaseTrainer._to_compute_dtype
    _cast_net_vars = BaseTrainer._cast_net_vars

    def __init__(self, dtype):
        self.compute_dtype = jnp.dtype(dtype)


def _net_vars():
    return {
        "params": {"conv": {"kernel": jnp.ones((3, 3, 4, 8), jnp.float32),
                            "bias": jnp.zeros((8,), jnp.float32)}},
        "batch_stats": {"bn": {"mean": jnp.zeros((8,), jnp.float32)}},
        "spectral": {"conv": {"u": jnp.ones((8,), jnp.float32)}},
    }


class TestCastNetVars:
    def test_params_only(self):
        out = _Caster("bfloat16")._cast_net_vars(_net_vars())
        assert out["params"]["conv"]["kernel"].dtype == jnp.bfloat16
        assert out["params"]["conv"]["bias"].dtype == jnp.bfloat16
        # the fp32 islands keep their dtype
        assert out["batch_stats"]["bn"]["mean"].dtype == jnp.float32
        assert out["spectral"]["conv"]["u"].dtype == jnp.float32

    def test_fp32_policy_is_identity(self):
        v = _net_vars()
        assert _Caster("float32")._cast_net_vars(v) is v
        assert _Caster("bfloat16")._cast_net_vars(None) is None

    def test_non_float_leaves_untouched(self):
        v = {"params": {"step": jnp.zeros((), jnp.int32)}}
        out = _Caster("bfloat16")._cast_net_vars(v)
        assert out["params"]["step"].dtype == jnp.int32


class TestSpectralNormIsland:
    def test_power_iteration_fp32_from_bf16_weights(self, rng):
        w = jnp.asarray(rng.randn(8, 12).astype(np.float32))
        u = jnp.asarray(rng.randn(8).astype(np.float32))
        sigma, new_u = power_iteration(w.astype(jnp.bfloat16), u)
        assert sigma.dtype == jnp.float32
        assert new_u.dtype == jnp.float32
        sigma32, _ = power_iteration(w, u)
        # iteration ran on the (rounded) bf16 weights but in fp32 math
        np.testing.assert_allclose(float(sigma), float(sigma32), rtol=2e-2)

    def test_power_iteration_refuses_bf16_u(self, rng):
        w = jnp.asarray(rng.randn(4, 6).astype(np.float32))
        u = jnp.ones((4,), jnp.bfloat16)
        from imaginaire_tpu.analysis import islands

        with pytest.raises(islands.IslandViolation, match="float32"):
            power_iteration(w, u)

    def test_estimate_sigma_fp32_from_bf16(self, rng):
        k = jnp.asarray(rng.randn(3, 3, 4, 8).astype(np.float32))
        u = jnp.asarray(rng.randn(8).astype(np.float32))
        sigma = estimate_sigma(k.astype(jnp.bfloat16), u.astype(jnp.bfloat16))
        assert sigma.dtype == jnp.float32

    def test_spectral_normalize_bf16_kernel_keeps_dtype(self, rng):
        class SN(nn.Module):
            @nn.compact
            def __call__(self, training=False):
                k = self.param(
                    "kernel", nn.initializers.normal(1.0), (3, 3, 4, 8))
                return spectral_normalize(
                    self, k.astype(jnp.bfloat16), training)

        variables = SN().init(jax.random.PRNGKey(0))
        out = SN().apply(variables, training=False)
        # no silent promotion back to fp32 downstream of the divide...
        assert out.dtype == jnp.bfloat16
        # ...and the stored u vector is an fp32 island
        assert variables["spectral"]["u"].dtype == jnp.float32


class TestNormStatIslands:
    @pytest.mark.parametrize("norm_cls", [InstanceNorm, LayerNorm2d])
    def test_bf16_in_bf16_out_fp32_stats(self, rng, norm_cls):
        x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
        mod = norm_cls()
        variables = mod.init(jax.random.PRNGKey(0), x)
        out16 = mod.apply(variables, x.astype(jnp.bfloat16))
        assert out16.dtype == jnp.bfloat16
        out32 = mod.apply(variables, x)
        assert out32.dtype == jnp.float32
        # same statistics path: bf16 output is the rounded fp32 result
        np.testing.assert_allclose(np.asarray(out16, np.float32),
                                   np.asarray(out32), atol=4e-2)


class TestAuditNormIsland:
    def test_tree_norm_accumulates_fp32(self, rng):
        from imaginaire_tpu.diagnostics.audit import tree_norm

        leaves = {"a": jnp.asarray(rng.randn(64).astype(np.float32)),
                  "b": jnp.asarray(rng.randn(32).astype(np.float32))}
        want = float(tree_norm(leaves))
        got = tree_norm(jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), leaves))
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(float(got), want, rtol=1e-2)


class TestTrainerResolution:
    def _trainer(self, mutate):
        cfg = Config(CFG_PATH)
        cfg.trainer.perceptual_loss.allow_random_init = True
        mutate(cfg)
        from imaginaire_tpu.registry import resolve

        return resolve(cfg.trainer.type, "Trainer")(cfg)

    def test_structured_knob_wins(self):
        def mutate(cfg):
            cfg.trainer.compute_dtype = "float32"  # legacy scalar loses
            cfg.trainer.mixed_precision = {"enabled": True,
                                           "compute_dtype": "bfloat16"}

        t = self._trainer(mutate)
        assert t.compute_dtype == jnp.bfloat16
        assert t.mixed_precision is True

    def test_disabled_falls_back_to_legacy_scalar(self):
        def mutate(cfg):
            cfg.trainer.compute_dtype = "bfloat16"
            cfg.trainer.mixed_precision = {"enabled": False}

        t = self._trainer(mutate)
        assert t.compute_dtype == jnp.bfloat16

        t = self._trainer(lambda cfg: None)  # seed default: fp32 end to end
        assert t.compute_dtype == jnp.float32
        assert t.mixed_precision is False

"""Trainer harness tests: 2-iteration SPADE training on synthetic data
(mirrors the reference's scripts/test_training.sh 2-iter smoke strategy,
SURVEY.md §4) plus optimizer/EMA unit tests."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from imaginaire_tpu.config import AttrDict, Config
from imaginaire_tpu.optim import fromage, get_optimizer_for_params, get_scheduler, madam
from imaginaire_tpu.utils.model_average import collapse_spectral_norm, ema_init, ema_update

CFG_PATH = os.path.join(os.path.dirname(__file__), "..", "configs", "unit_test", "spade.yaml")
CFG_P2P = os.path.join(os.path.dirname(__file__), "..", "configs", "unit_test", "pix2pixHD.yaml")


def synthetic_batch(rng, h=256, w=256, labels=14):
    # 12 seg channels + 1 dont-care + 1 edge = 14 label channels.
    return {
        "images": jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32)) * 2 - 1,
        "label": jnp.asarray((rng.rand(1, h, w, labels) > 0.9).astype(np.float32)),
    }


class TestOptimizers:
    def test_fromage_matches_reference_step(self, rng):
        lr = 0.01
        p = {"w": jnp.asarray(rng.randn(4, 3).astype(np.float32))}
        g = {"w": jnp.asarray(rng.randn(4, 3).astype(np.float32))}
        tx = fromage(lr)
        upd, _ = tx.update(g, tx.init(p), p)
        new_p = optax.apply_updates(p, upd)
        pw, gw = np.asarray(p["w"]), np.asarray(g["w"])
        want = (pw - lr * gw * (np.linalg.norm(pw) / np.linalg.norm(gw)))
        want /= np.sqrt(1 + lr ** 2)
        np.testing.assert_allclose(np.asarray(new_p["w"]), want, rtol=1e-5)

    def test_madam_bounded_multiplicative(self, rng):
        p = {"w": jnp.asarray(rng.randn(8).astype(np.float32))}
        tx = madam(0.01, scale=3.0)
        state = tx.init(p)
        g = {"w": jnp.asarray(rng.randn(8).astype(np.float32))}
        upd, state = tx.update(g, state, p)
        new_p = optax.apply_updates(p, upd)
        bound = 3.0 * np.sqrt((np.asarray(p["w"]) ** 2).mean())
        assert np.all(np.abs(np.asarray(new_p["w"])) <= bound + 1e-6)
        # sign never flips under multiplicative update
        assert np.all(np.sign(new_p["w"]) == np.sign(p["w"]))

    def test_step_scheduler(self):
        cfg_opt = AttrDict({"lr_policy": {"type": "step", "step_size": 2, "gamma": 0.1}})
        sched = get_scheduler(cfg_opt, iters_per_epoch=10)
        assert sched(0) == 1.0
        assert sched(19) == 1.0
        np.testing.assert_allclose(sched(20), 0.1)
        np.testing.assert_allclose(sched(45), 0.01)

    def test_factory_adam(self):
        cfg_opt = AttrDict({"type": "adam", "lr": 1e-3, "adam_beta1": 0.5})
        tx = get_optimizer_for_params(cfg_opt)
        p = {"w": jnp.ones(3)}
        upd, _ = tx.update({"w": jnp.ones(3)}, tx.init(p), p)
        assert np.all(np.isfinite(np.asarray(upd["w"])))


class TestEMA:
    def test_copy_then_average(self):
        p = {"k": jnp.ones(3)}
        avg = ema_init(p, None, remove_sn=False)
        # before start_iteration: pure copy of source
        p2 = {"k": jnp.full((3,), 2.0)}
        avg = ema_update(avg, p2, num_updates=1, beta=0.9, start_iteration=5,
                         remove_sn=False)
        np.testing.assert_allclose(avg["k"], 2.0)
        # after: exponential average
        p3 = {"k": jnp.full((3,), 3.0)}
        avg = ema_update(avg, p3, num_updates=10, beta=0.9, start_iteration=5,
                         remove_sn=False)
        np.testing.assert_allclose(np.asarray(avg["k"]), 0.9 * 2.0 + 0.1 * 3.0, rtol=1e-6)

    def test_sn_collapse_divides_by_sigma(self, rng):
        k = rng.randn(3, 3, 4, 8).astype(np.float32)
        params = {"conv": {"kernel": jnp.asarray(k), "bias": jnp.zeros(8)}}
        u = rng.randn(8).astype(np.float32)
        u /= np.linalg.norm(u)
        spectral = {"conv": {"u": jnp.asarray(u)}}
        out = collapse_spectral_norm(params, spectral)
        w = k.reshape(-1, 8).T
        v = w.T @ u
        v /= np.linalg.norm(v)
        u2 = w @ v
        u2 /= np.linalg.norm(u2)
        sigma = u2 @ w @ v
        np.testing.assert_allclose(np.asarray(out["conv"]["kernel"]),
                                   k / sigma, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(out["conv"]["bias"]), 0.0)


@pytest.mark.slow
class TestSPADETraining:
    def test_two_iterations(self, rng, tmp_path):
        cfg = Config(CFG_PATH)
        cfg.logdir = str(tmp_path)
        # shrink for test speed
        from imaginaire_tpu.registry import resolve

        trainer_cls = resolve(cfg.trainer.type, "Trainer")
        trainer = trainer_cls(cfg)
        data = synthetic_batch(rng)
        key = jax.random.PRNGKey(0)
        trainer.init_state(key, data)

        trainer.start_of_epoch(0)
        losses_hist = []
        for it in range(1, 3):
            batch = trainer.start_of_iteration(synthetic_batch(rng), it)
            d_losses = trainer.dis_update(batch)
            g_losses = trainer.gen_update(batch)
            trainer.end_of_iteration(batch, 0, it)
            losses_hist.append((d_losses, g_losses))
        for d_losses, g_losses in losses_hist:
            for name, v in {**d_losses, **g_losses}.items():
                assert np.isfinite(float(jax.device_get(v))), name
        # all loss terms present
        assert {"GAN", "FeatureMatching", "GaussianKL", "Perceptual", "total"} <= set(
            losses_hist[0][1].keys())

    def test_int_label_on_device_onehot(self, rng, tmp_path):
        """(B,H,W) int label maps are one-hot expanded inside the jitted
        step — the TPU-idiomatic H2D path (ships KBs, not one-hot MBs)."""
        cfg = Config(CFG_PATH)
        cfg.logdir = str(tmp_path)
        from imaginaire_tpu.registry import resolve

        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = {
            "images": jnp.asarray(rng.rand(1, 256, 256, 3).astype(np.float32)) * 2 - 1,
            "label": jnp.asarray(rng.randint(0, 14, (1, 256, 256)).astype(np.int32)),
        }
        trainer.init_state(jax.random.PRNGKey(0), data)
        batch = trainer.start_of_iteration(data, 1)
        d = trainer.dis_update(batch)
        g = trainer.gen_update(batch)
        for name, v in {**d, **g}.items():
            assert np.isfinite(float(jax.device_get(v))), name

    def test_image_kid_prdc_and_real_act_cache(self, rng, tmp_path):
        """Image-family KID/PRDC through the base template
        (trainers/base.py::compute_extra_metrics + the spade activations
        hook), and the cross-checkpoint real-activation cache."""
        from imaginaire_tpu.registry import resolve

        cfg = Config(CFG_PATH)
        cfg.logdir = str(tmp_path)
        cfg.trainer.fid_random_init = True
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        # KID's unbiased MMD needs >= 2 samples per set
        trainer.val_data_loader = [synthetic_batch(rng),
                                   synthetic_batch(rng)]
        trainer.init_state(jax.random.PRNGKey(0), synthetic_batch(rng))
        out = trainer.compute_extra_metrics(["kid", "prdc"])
        assert np.isfinite(out["KID"])
        assert {"PRDC_precision", "PRDC_recall", "PRDC_density",
                "PRDC_coverage"} <= set(out)
        assert trainer.compute_extra_metrics(["bogus"]) == {}

        # cache helper: second call must reuse the saved activations
        # (random-init extractors skip caching, so flip the flag off —
        # on trainer.cfg: the trainer holds an as_attrdict copy)
        trainer.cfg.trainer.fid_random_init = False
        calls = []

        def compute():
            calls.append(1)
            return np.full((3, 4), 7.0, np.float32)

        a1 = trainer._cached_real_activations("real_acts_t.npz", compute)
        a2 = trainer._cached_real_activations("real_acts_t.npz", compute)
        assert len(calls) == 1
        np.testing.assert_array_equal(a1, a2)
        # stale graph version -> recompute
        import os

        from imaginaire_tpu.evaluation.fid import FEATURE_GRAPH_VERSION

        path = os.path.join(str(tmp_path), "real_acts_t.npz")
        np.savez(path, acts=np.zeros((3, 4)), graph_version=-1)
        a3 = trainer._cached_real_activations("real_acts_t.npz", compute)
        assert len(calls) == 2
        np.testing.assert_array_equal(a3, a1)
        assert int(np.load(path)["graph_version"]) == FEATURE_GRAPH_VERSION

    def test_bf16_policy_parity(self, rng, tmp_path):
        """bf16 compute policy: losses must stay close to fp32 and params
        must remain fp32 masters (the AMP replacement, SURVEY §2.2)."""
        from imaginaire_tpu.registry import resolve

        results = {}
        for dtype in ("float32", "bfloat16"):
            cfg = Config(CFG_PATH)
            cfg.logdir = str(tmp_path / dtype)
            cfg.trainer.compute_dtype = dtype
            trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
            data = synthetic_batch(rng)
            trainer.init_state(jax.random.PRNGKey(0), data)
            batch = trainer.start_of_iteration(synthetic_batch(np.random.RandomState(1)), 1)
            g_losses = trainer.gen_update(batch)
            results[dtype] = {k: float(jax.device_get(v)) for k, v in g_losses.items()}
            # master params stay fp32
            for leaf in jax.tree_util.tree_leaves(trainer.state["vars_G"]["params"]):
                assert leaf.dtype == jnp.float32
        for name in results["float32"]:
            a, b = results["float32"][name], results["bfloat16"][name]
            assert np.isfinite(b), name
            assert abs(a - b) <= 0.05 * max(1.0, abs(a)), (name, a, b)

    def test_dis_spectral_u_updates(self, rng, tmp_path):
        """D's power-iteration vector u must advance on every dis step
        (torch spectral_norm updates weight_u on each training forward)."""
        cfg = Config(CFG_PATH)
        cfg.logdir = str(tmp_path)
        from imaginaire_tpu.registry import resolve

        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = synthetic_batch(rng)
        trainer.init_state(jax.random.PRNGKey(0), data)
        assert "spectral" in trainer.state["vars_D"], "D has no spectral state"
        # materialize on host BEFORE the step: the jitted step donates the
        # state pytree, invalidating the old device buffers.
        u_before = [np.asarray(x) for x in
                    jax.tree_util.tree_leaves(trainer.state["vars_D"]["spectral"])]
        batch = trainer.start_of_iteration(synthetic_batch(rng), 1)
        trainer.dis_update(batch)
        u_after = [np.asarray(x) for x in
                   jax.tree_util.tree_leaves(trainer.state["vars_D"]["spectral"])]
        assert any(not np.allclose(x, y) for x, y in zip(u_before, u_after)), \
            "spectral u frozen across dis_update"

    def test_pix2pixHD_two_iterations(self, rng, tmp_path):
        """pix2pixHD: edge preprocessing + encoder path + no-KL loss set
        (ref: trainers/pix2pixHD.py:49-157)."""
        cfg = Config(CFG_P2P)
        cfg.logdir = str(tmp_path)
        from imaginaire_tpu.registry import resolve

        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)

        def batch(r):
            # 8 seg channels + 1 instance-id channel
            seg = (r.rand(1, 128, 128, 8) > 0.9).astype(np.float32)
            inst = r.randint(0, 5, (1, 128, 128, 1)).astype(np.float32)
            return {
                "images": jnp.asarray(r.rand(1, 128, 128, 3).astype(np.float32)) * 2 - 1,
                "label": jnp.asarray(np.concatenate([seg, inst], axis=-1)),
            }

        trainer.init_state(jax.random.PRNGKey(0), batch(rng))
        trainer.start_of_epoch(0)
        for it in range(1, 3):
            b = trainer.start_of_iteration(batch(rng), it)
            d = trainer.dis_update(b)
            g = trainer.gen_update(b)
            trainer.end_of_iteration(b, 0, it)
        for name, v in {**d, **g}.items():
            assert np.isfinite(float(jax.device_get(v))), name
        assert "GaussianKL" not in trainer.weights
        assert {"GAN", "FeatureMatching", "Perceptual", "total"} <= set(g)
        # preprocessing swapped the instance channel for a binary edge map
        assert set(np.unique(np.asarray(b["label"][..., -1]))) <= {0.0, 1.0}
        assert "instance_maps" in b

    def test_pix2pixHD_cluster_checkpoint(self, rng, tmp_path):
        """_pre_save_checkpoint K-means features land in the state
        (ref: trainers/pix2pixHD.py:159-173)."""
        cfg = Config(CFG_P2P)
        cfg.logdir = str(tmp_path)
        from imaginaire_tpu.registry import resolve

        def batch(r):
            seg = (r.rand(1, 128, 128, 8) > 0.9).astype(np.float32)
            inst = np.zeros((1, 128, 128, 1), np.float32)
            inst[:, 64:, :, :] = 3.0  # two large instances
            return {
                "images": r.rand(1, 128, 128, 3).astype(np.float32) * 2 - 1,
                "label": np.concatenate([seg, inst], axis=-1),
            }

        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        trainer.val_data_loader = [batch(rng)]
        trainer.init_state(jax.random.PRNGKey(0), batch(rng))
        trainer.save_checkpoint(0, 1)
        centers = np.asarray(trainer.state["cluster_centers"])
        assert centers.shape == (9, 4, 3)
        assert np.abs(centers).sum() > 0

    def test_checkpoint_roundtrip(self, rng, tmp_path):
        cfg = Config(CFG_PATH)
        cfg.logdir = str(tmp_path)
        cfg.trainer.model_average = True
        cfg.trainer.model_average_start_iteration = 1
        from imaginaire_tpu.registry import resolve

        trainer_cls = resolve(cfg.trainer.type, "Trainer")
        trainer = trainer_cls(cfg)
        data = synthetic_batch(rng)
        trainer.init_state(jax.random.PRNGKey(0), data)
        batch = trainer.start_of_iteration(synthetic_batch(rng), 1)
        trainer.gen_update(batch)
        trainer.save_checkpoint(0, 1)

        trainer2 = trainer_cls(cfg)
        trainer2.init_state(jax.random.PRNGKey(1), data)
        assert trainer2.load_checkpoint()
        a = jax.tree_util.tree_leaves(trainer.state["vars_G"]["params"])
        b = jax.tree_util.tree_leaves(trainer2.state["vars_G"]["params"])
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)
        assert trainer2.current_iteration == 1


@pytest.mark.slow
class TestEmaBatchNormRecalibration:
    def test_recalibrated_stats_differ_and_flow_to_inference(self, rng,
                                                             tmp_path):
        """EMA BN stats are re-estimated as the cumulative mean of
        per-batch statistics (ref: trainers/base.py:415-443,
        utils/model_average.py:9-33)."""
        cfg = Config(CFG_PATH)
        cfg.logdir = str(tmp_path)
        cfg.trainer.model_average = True
        cfg.trainer.model_average_start_iteration = 1
        cfg.trainer.model_average_batch_norm_estimation_iteration = 2
        cfg.gen.global_adaptive_norm_type = "sync_batch"
        cfg.gen.activation_norm_params.activation_norm_type = "sync_batch"
        from imaginaire_tpu.registry import resolve

        batches = [synthetic_batch(rng, h=64, w=64) for _ in range(3)]
        trainer = resolve(cfg.trainer.type, "Trainer")(
            cfg, train_data_loader=batches)
        trainer.init_state(jax.random.PRNGKey(0), batches[0])
        b = trainer.start_of_iteration(batches[0], 1)
        trainer.dis_update(b)
        trainer.gen_update(b)
        assert trainer.state["vars_G"].get("batch_stats"), \
            "config change should give the generator BN stats"
        trainer.recalculate_model_average_batch_norm_statistics()
        assert trainer._ema_batch_stats is not None
        live = trainer.state["vars_G"]["batch_stats"]
        recal = trainer._ema_batch_stats
        diffs = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), live, recal)
        assert max(jax.tree_util.tree_leaves(diffs)) > 1e-6
        variables = trainer.inference_params()
        chex_same = jax.tree_util.tree_structure(
            variables["batch_stats"]) == jax.tree_util.tree_structure(recal)
        assert chex_same
        out, _ = trainer._apply_G(variables, trainer._init_data(batches[0]),
                                  jax.random.PRNGKey(1), training=False)
        assert np.all(np.isfinite(np.asarray(out["fake_images"])))
        # recalibrated stats survive a checkpoint round-trip
        trainer.save_checkpoint(0, 1)
        fresh = resolve(cfg.trainer.type, "Trainer")(
            cfg, train_data_loader=batches)
        fresh.init_state(jax.random.PRNGKey(0), batches[0])
        assert fresh.load_checkpoint()
        assert getattr(fresh, "_ema_batch_stats", None) is not None
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(fresh._ema_batch_stats)[0]),
            np.asarray(jax.tree_util.tree_leaves(recal)[0]), rtol=1e-6)

"""Data subsystem tests: folder backend, augmentor, one-hot w/ dont-care,
label concat, loader sharding, packed backend round-trip."""

import os

import numpy as np
import pytest

from imaginaire_tpu.config import Config
from imaginaire_tpu.data.backends import PackedBackend, build_packed_dataset
from imaginaire_tpu.data.loader import DataLoader, get_train_and_val_dataloader
from imaginaire_tpu.data.paired_images import Dataset as PairedImages

CFG_PATH = os.path.join(os.path.dirname(__file__), "..", "configs",
                        "unit_test", "spade.yaml")
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "spade", "raw")


@pytest.fixture
def cfg():
    c = Config(CFG_PATH)
    # point roots at the fixture dir regardless of cwd
    c.data.train.roots = [FIXTURES]
    c.data.val.roots = [FIXTURES]
    return c


class TestPairedImages:
    def test_item_shapes_and_ranges(self, cfg):
        ds = PairedImages(cfg)
        assert len(ds) == 3
        item = ds[0]
        # 12 seg + 1 dont-care + 1 edge = 14 label channels.
        assert item["label"].shape == (256, 256, 14)
        assert item["images"].shape == (256, 256, 3)
        assert item["images"].min() >= -1.0 and item["images"].max() <= 1.0
        # one-hot: each pixel's seg channels sum to 1
        seg = item["label"][..., :13]
        np.testing.assert_allclose(seg.sum(-1), 1.0)
        assert item["key"].startswith("seq0001/")

    def test_dont_care_encoding(self, cfg):
        ds = PairedImages(cfg)
        # fixture writes 255 into the top-left corner -> dont-care channel 12
        cfg.data.val.augmentations = {"center_crop_h_w": "256, 256"}
        ds_val = PairedImages(cfg, is_inference=True)
        item = ds_val[0]
        assert item["label"].shape[-1] == 14

    def test_label_lengths(self, cfg):
        ds = PairedImages(cfg)
        assert ds.get_label_lengths() == {"seg_maps": 13, "edge_maps": 1}

    def test_augmentation_determinism_of_shapes(self, cfg):
        ds = PairedImages(cfg)
        for i in range(3):
            item = ds[i]
            assert item["images"].shape == (256, 256, 3)


class TestLoader:
    def test_batching(self, cfg):
        train, val = get_train_and_val_dataloader(cfg)
        batch = next(iter(train))
        assert batch["images"].shape == (1, 256, 256, 3)
        assert batch["label"].shape == (1, 256, 256, 14)
        assert len(train) == 3

    def test_epoch_reshuffle(self, cfg):
        ds = PairedImages(cfg)
        loader = DataLoader(ds, batch_size=1, shuffle=True, seed=1)
        loader.set_epoch(0)
        keys0 = [b["key"][0] for b in loader]
        loader.set_epoch(1)
        keys1 = [b["key"][0] for b in loader]
        assert sorted(keys0) == sorted(keys1)


class TestPackedBackend:
    def test_roundtrip(self, cfg, tmp_path):
        out = build_packed_dataset(FIXTURES, str(tmp_path / "packed"),
                                   ["images", "seg_maps", "edge_maps"])
        backend = PackedBackend(os.path.join(out, "images"))
        img = backend.getitem("seq0001/00000")
        assert img.shape == (300, 320, 3)
        # packed dataset is directly usable by the Dataset class
        cfg.data.train.roots = [out]
        cfg.data.train.is_packed = True
        ds = PairedImages(cfg)
        item = ds[0]
        assert item["images"].shape == (256, 256, 3)

"""Data subsystem tests: folder backend, augmentor, one-hot w/ dont-care,
label concat, loader sharding, packed backend round-trip."""

import os

import numpy as np
import pytest

from imaginaire_tpu.config import Config
from imaginaire_tpu.data.backends import PackedBackend, build_packed_dataset
from imaginaire_tpu.data.loader import DataLoader, get_train_and_val_dataloader
from imaginaire_tpu.data.paired_images import Dataset as PairedImages

CFG_PATH = os.path.join(os.path.dirname(__file__), "..", "configs",
                        "unit_test", "spade.yaml")
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "spade", "raw")


@pytest.fixture
def cfg():
    c = Config(CFG_PATH)
    # point roots at the fixture dir regardless of cwd
    c.data.train.roots = [FIXTURES]
    c.data.val.roots = [FIXTURES]
    return c


class TestPairedImages:
    def test_item_shapes_and_ranges(self, cfg):
        ds = PairedImages(cfg)
        assert len(ds) == 3
        item = ds[0]
        # 12 seg + 1 dont-care + 1 edge = 14 label channels.
        assert item["label"].shape == (256, 256, 14)
        assert item["images"].shape == (256, 256, 3)
        assert item["images"].min() >= -1.0 and item["images"].max() <= 1.0
        # one-hot: each pixel's seg channels sum to 1
        seg = item["label"][..., :13]
        np.testing.assert_allclose(seg.sum(-1), 1.0)
        assert item["key"].startswith("seq0001/")

    def test_dont_care_encoding(self, cfg):
        ds = PairedImages(cfg)
        # fixture writes 255 into the top-left corner -> dont-care channel 12
        cfg.data.val.augmentations = {"center_crop_h_w": "256, 256"}
        ds_val = PairedImages(cfg, is_inference=True)
        item = ds_val[0]
        assert item["label"].shape[-1] == 14

    def test_label_lengths(self, cfg):
        ds = PairedImages(cfg)
        assert ds.get_label_lengths() == {"seg_maps": 13, "edge_maps": 1}

    def test_augmentation_determinism_of_shapes(self, cfg):
        ds = PairedImages(cfg)
        for i in range(3):
            item = ds[i]
            assert item["images"].shape == (256, 256, 3)


class TestLoader:
    def test_batching(self, cfg):
        train, val = get_train_and_val_dataloader(cfg)
        batch = next(iter(train))
        assert batch["images"].shape == (1, 256, 256, 3)
        assert batch["label"].shape == (1, 256, 256, 14)
        assert len(train) == 3

    def test_epoch_reshuffle(self, cfg):
        ds = PairedImages(cfg)
        loader = DataLoader(ds, batch_size=1, shuffle=True, seed=1)
        loader.set_epoch(0)
        keys0 = [b["key"][0] for b in loader]
        loader.set_epoch(1)
        keys1 = [b["key"][0] for b in loader]
        assert sorted(keys0) == sorted(keys1)


class TestPackedBackend:
    def test_roundtrip(self, cfg, tmp_path):
        out = build_packed_dataset(FIXTURES, str(tmp_path / "packed"),
                                   ["images", "seg_maps", "edge_maps"])
        backend = PackedBackend(os.path.join(out, "images"))
        img = backend.getitem("seq0001/00000")
        assert img.shape == (300, 320, 3)
        # packed dataset is directly usable by the Dataset class
        cfg.data.train.roots = [out]
        cfg.data.train.is_packed = True
        ds = PairedImages(cfg)
        item = ds[0]
        assert item["images"].shape == (256, 256, 3)


class TestNativeIO:
    def test_native_reader_matches_python(self, tmp_path):
        """The C++ thread-pool reader returns byte-identical payloads to
        Python IO, single and batched."""
        import numpy as np

        from imaginaire_tpu.native import NativeBlobReader, load_library

        if load_library() is None:
            import pytest

            pytest.skip("no native toolchain")
        blob = tmp_path / "data.bin"
        rng = np.random.RandomState(0)
        payloads = [rng.bytes(rng.randint(10, 5000)) for _ in range(20)]
        extents = []
        with open(blob, "wb") as f:
            for p in payloads:
                extents.append((f.tell(), len(p)))
                f.write(p)
        r = NativeBlobReader(str(blob))
        for (off, length), want in zip(extents, payloads):
            assert r.read(off, length) == want
        got = r.read_batch(extents)
        assert got == payloads
        r.close()

    def test_packed_backend_native_path(self, tmp_path):
        """PackedBackend serves images through the native reader."""
        import numpy as np
        from PIL import Image

        from imaginaire_tpu.data.backends import (
            PackedBackend,
            build_packed_dataset,
        )

        raw = tmp_path / "raw"
        for i in range(3):
            d = raw / "images" / "seqA"
            d.mkdir(parents=True, exist_ok=True)
            Image.fromarray(
                np.random.RandomState(i).randint(0, 255, (8, 8, 3),
                                                 np.uint8)).save(
                d / f"{i:05d}.png")
        out = build_packed_dataset(str(raw), str(tmp_path / "packed"),
                                   ["images"])
        be = PackedBackend(str(tmp_path / "packed" / "images"))
        img = be.getitem("seqA/00000")
        assert img.shape == (8, 8, 3)
        imgs = be.getitems(["seqA/00000", "seqA/00002"])
        assert len(imgs) == 2 and imgs[1].shape == (8, 8, 3)

    def test_loader_num_workers_same_batches(self):
        """Prefetching workers yield the same batches as the serial path."""
        import numpy as np

        from imaginaire_tpu.data.loader import DataLoader

        class DS:
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return {"x": np.full((2, 2), i, np.float32), "key": str(i)}

        serial = list(DataLoader(DS(), 2, shuffle=True, seed=3))
        threaded = list(DataLoader(DS(), 2, shuffle=True, seed=3,
                                   num_workers=4))
        assert len(serial) == len(threaded) == 5
        for a, b in zip(serial, threaded):
            np.testing.assert_array_equal(a["x"], b["x"])
            assert a["key"] == b["key"]

    def test_loader_early_abandon_no_deadlock(self):
        """next(iter(loader)) then dropping the iterator must not hang
        (train.py fetches one sample batch before the epoch loop)."""
        import numpy as np

        from imaginaire_tpu.data.loader import DataLoader

        class DS:
            def __len__(self):
                return 50

            def __getitem__(self, i):
                return {"x": np.zeros((4,), np.float32)}

        loader = DataLoader(DS(), 2, num_workers=4, prefetch_batches=2)
        first = next(iter(loader))  # iterator abandoned immediately
        assert first["x"].shape == (2, 4)
        # breaking mid-epoch must also unwind cleanly
        for i, _ in enumerate(loader):
            if i == 1:
                break

    def test_loader_worker_exception_propagates(self):
        """A failing sample must raise in the consumer, not hang."""
        import numpy as np
        import pytest

        from imaginaire_tpu.data.loader import DataLoader

        class DS:
            def __len__(self):
                return 10

            def __getitem__(self, i):
                if i == 3:
                    raise ValueError("corrupt sample")
                return {"x": np.zeros((4,), np.float32)}

        loader = DataLoader(DS(), 2, shuffle=False, num_workers=2)
        with pytest.raises(ValueError, match="corrupt sample"):
            list(loader)


def _video_cfg(tmp_path, n_frames=40, seq_len=3, max_time_step=3,
               dataset_type="imaginaire_tpu.data.paired_videos",
               extra_train=None, extra_data=None):
    """A folder-backed video config over a synthetic sequence of
    ``n_frames`` (never actually decoded — tests stub load_item)."""
    seq_dir = tmp_path / "raw" / "images" / "seq0"
    seq_dir.mkdir(parents=True, exist_ok=True)
    for i in range(n_frames):
        (seq_dir / f"{i:05d}.jpg").touch()
    c = Config(CFG_PATH)
    train = {"roots": [str(tmp_path / "raw")], "batch_size": 1,
             "initial_sequence_length": seq_len,
             "augmentations": {"resize_h_w": "16, 16",
                               "max_time_step": max_time_step}}
    train.update(extra_train or {})
    c.data = type(c.data)(dict(extra_data or {}, **{
        "name": "stride_fixture",
        "type": dataset_type,
        "num_frames_G": seq_len,
        "num_workers": 0,
        "input_types": [
            {"images": {"ext": "jpg", "num_channels": 3,
                        "interpolator": "BILINEAR", "normalize": True}}],
        "input_image": ["images"],
        "input_labels": [],
        "train": train,
        "val": {"roots": [str(tmp_path / "raw")], "batch_size": 1,
                "augmentations": {"resize_h_w": "16, 16"}},
    }))
    return c


def _stub_io(ds):
    """Bypass decode: __getitem__ returns the chosen frame stems."""
    ds.load_item = lambda root_idx, seq, frames: {"images": list(frames)}
    ds.process_item = lambda raw, thread_common_attr=True: raw
    ds.concat_labels = lambda out, squeeze_time=False: out
    return ds


class TestTemporalStride:
    """max_time_step strided window sampling
    (ref: datasets/paired_videos.py:167-191)."""

    def test_window_indices_honor_stride(self, tmp_path):
        import random

        from imaginaire_tpu.registry import resolve

        cfg = _video_cfg(tmp_path, n_frames=40, seq_len=3, max_time_step=3)
        ds = _stub_io(resolve(cfg.data.type, "Dataset")(cfg))
        random.seed(7)
        strides = set()
        for draw in range(60):
            frames = ds[draw]["images"]
            assert len(frames) == 3
            idx = [int(s) for s in frames]
            assert 0 <= idx[0] and idx[-1] < 40
            diffs = {b - a for a, b in zip(idx, idx[1:])}
            assert len(diffs) == 1, "stride must be constant in a window"
            step = diffs.pop()
            assert 1 <= step <= 3
            strides.add(step)
        assert strides == {1, 2, 3}, \
            f"all strides in [1, max_time_step] should occur, got {strides}"

    def test_stride_falls_back_when_window_exceeds_longest(self, tmp_path):
        import random

        from imaginaire_tpu.registry import resolve

        # seq_len=5: stride s needs 1+4s frames; only s<=2 fits 12
        cfg = _video_cfg(tmp_path, n_frames=12, seq_len=5, max_time_step=10)
        ds = _stub_io(resolve(cfg.data.type, "Dataset")(cfg))
        random.seed(3)
        for draw in range(40):
            frames = ds[draw]["images"]
            assert len(frames) == 5
            idx = [int(s) for s in frames]
            step = idx[1] - idx[0]
            assert step in (1, 2)
            assert idx[-1] < 12

    def test_few_shot_stride_and_disjoint_refs(self, tmp_path):
        import random

        from imaginaire_tpu.registry import resolve

        cfg = _video_cfg(
            tmp_path, n_frames=40, seq_len=3, max_time_step=3,
            dataset_type="imaginaire_tpu.data.paired_few_shot_videos",
            extra_data={"initial_few_shot_K": 2})
        ds = _stub_io(resolve(cfg.data.type, "Dataset")(cfg))
        random.seed(11)
        strides = set()
        for draw in range(60):
            item = ds[draw]
            frames = [int(s) for s in item["images"]]
            refs = [int(s) for s in item["ref_images"]]
            assert len(frames) == 3 and len(refs) == 2
            step = frames[1] - frames[0]
            assert frames[2] - frames[1] == step and 1 <= step <= 3
            strides.add(step)
            # refs disjoint from the RAW window [start, end), not just
            # the strided picks (ref: paired_few_shot_videos.py:182-189)
            lo, hi = frames[0], frames[0] + (len(frames) - 1) * step + 1
            assert all(r < lo or r >= hi for r in refs)
        assert strides == {1, 2, 3}

    def test_knob_never_parses_without_effect(self, cfg):
        """A non-video dataset handed max_time_step>1 must refuse it."""
        cfg.data.train.augmentations.max_time_step = 2
        with pytest.raises(ValueError, match="max_time_step"):
            PairedImages(cfg)


class TestOneHotOnDevice:
    """one_hot_on_device: the host ships int index maps + float extras
    and the trainer's device-side one-hot must reproduce the host
    encoding exactly (data/base.py::_encode_index_map,
    trainers/spade.py::_expand_labels)."""

    def _pair(self, cfg):
        cfg.data.val.augmentations = {"center_crop_h_w": "256, 256"}
        host = PairedImages(cfg, is_inference=True)
        cfg.data.one_hot_on_device = True
        dev = PairedImages(cfg, is_inference=True)
        return host[0], dev[0]

    def test_matches_host_onehot(self, cfg):
        import jax.numpy as jnp

        a, b = self._pair(cfg)
        assert b["label"].dtype == np.int32
        assert b["label"].shape == (256, 256)
        assert b["label_float"].shape == (256, 256, 1)
        # device-side expansion: 13 = 12 seg + dont-care
        onehot = np.asarray(jnp.asarray(
            np.eye(13, dtype=np.float32)[b["label"]]))
        recombined = np.concatenate([onehot, b["label_float"]], axis=-1)
        np.testing.assert_array_equal(recombined, a["label"])

    def test_trainer_expand_labels_parity(self, cfg):
        """End-to-end through the SPADE trainer's _expand_labels."""
        import jax
        from imaginaire_tpu.registry import resolve

        a, b = self._pair(cfg)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = {"label": jax.numpy.asarray(b["label"][None]),
                "label_float": jax.numpy.asarray(b["label_float"][None])}
        out = trainer._expand_labels(data)
        assert "label_float" not in out
        np.testing.assert_allclose(np.asarray(out["label"]),
                                   a["label"][None], atol=1e-6)

    def test_video_types_refuse_knob(self):
        from imaginaire_tpu.data.paired_videos import Dataset as PairedVideos

        cfg = Config(os.path.join(os.path.dirname(__file__), "..", "configs",
                                  "unit_test", "vid2vid_street.yaml"))
        cfg.data.train.roots = [FIXTURES]
        cfg.data.one_hot_on_device = True
        with pytest.raises(ValueError, match="image datasets only"):
            PairedVideos(cfg)

"""The shared per-block remat policy surface (ISSUE 10,
imaginaire_tpu/optim/remat.py): one registry, one resolver, one error
message; wrapped blocks keep the checkpoint-compatible param tree and
match the unwrapped forward bit-for-bit on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu.layers import Res2dBlock
from imaginaire_tpu.optim.remat import (
    POLICIES,
    call_block,
    is_positional,
    remat_block,
    remat_block_cls,
    remat_hyper_block_cls,
    resolve_policy,
)

ENABLED = ("blocks", "dots_saveable", "save_nothing")


class TestRegistry:
    def test_registry_names(self):
        assert set(POLICIES) == {"none", "blocks", "dots_saveable",
                                 "save_nothing"}
        assert not POLICIES["none"].enabled
        for name in ENABLED:
            assert POLICIES[name].enabled

    def test_resolver_accepts_none_and_instances(self):
        assert resolve_policy(None).name == "none"
        pol = POLICIES["blocks"]
        assert resolve_policy(pol) is pol

    def test_one_error_message_names_the_knob(self):
        with pytest.raises(ValueError, match="gen.remat"):
            resolve_policy("block", where="gen.remat")
        # every valid name is listed in the message
        with pytest.raises(ValueError, match="dots_saveable"):
            resolve_policy("nope")

    def test_wrapped_class_cached_per_policy(self):
        a = remat_block_cls(Res2dBlock, "blocks")
        b = remat_block_cls(Res2dBlock, "blocks")
        c = remat_block_cls(Res2dBlock, "dots_saveable")
        assert a is b and a is not c
        assert remat_block_cls(Res2dBlock, "none") is Res2dBlock
        # hyper wrappers get their own cache slot
        assert remat_hyper_block_cls(Res2dBlock, "blocks") is not a

    def test_positional_marker_and_dispatch(self):
        plain = Res2dBlock(8, name="blk")
        assert not is_positional(plain)
        wrapped = remat_block_cls(Res2dBlock, "blocks")(8, name="blk")
        assert is_positional(wrapped)


@pytest.mark.parametrize("policy", ENABLED)
class TestPolicyParity:
    """Every enabled policy must be a pure memory/speed trade: identical
    param tree (checkpoint compatibility) and identical forward values
    against the unwrapped block."""

    def _init_and_apply(self, make, x, *cond):
        mod = make()
        variables = mod.init(jax.random.PRNGKey(0), x, *cond,
                             training=False)
        out = mod.apply(variables, x, *cond, training=False)
        return variables, out

    def test_res_block(self, rng, policy):
        x = jnp.asarray(rng.randn(1, 16, 16, 8).astype(np.float32))
        base_vars, base_out = self._init_and_apply(
            lambda: _Wrap("none"), x)
        pol_vars, pol_out = self._init_and_apply(lambda: _Wrap(policy), x)
        assert jax.tree_util.tree_structure(base_vars) \
            == jax.tree_util.tree_structure(pol_vars)
        np.testing.assert_allclose(np.asarray(base_out),
                                   np.asarray(pol_out), atol=1e-6)

    def test_grad_parity(self, rng, policy):
        """remat changes WHERE activations come from on the backward
        pass, never their values: grads match the unwrapped block."""
        x = jnp.asarray(rng.randn(1, 8, 8, 4).astype(np.float32))

        def loss(variables, mod):
            return jnp.sum(mod.apply(variables, x, training=False) ** 2)

        base = _Wrap("none", features=4)
        variables = base.init(jax.random.PRNGKey(0), x, training=False)
        g_base = jax.grad(loss)(variables, base)
        g_pol = jax.grad(loss)(variables, _Wrap(policy, features=4))
        for a, b in zip(jax.tree_util.tree_leaves(g_base),
                        jax.tree_util.tree_leaves(g_pol)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


class _Wrap:
    """Tiny harness module: one rematted Res2dBlock, fixed name so the
    param tree is policy-invariant."""

    def __new__(cls, policy, features=8):
        import flax.linen as nn

        class M(nn.Module):
            @nn.compact
            def __call__(self, x, training=False):
                return remat_block(Res2dBlock, policy, where="gen.remat",
                                   out_channels=features,
                                   name="res")(x, training=training)

        return M()


class TestFamilies:
    """The knob reaches every family's blocks through the same surface:
    spot-check one generator-side and one discriminator-side module per
    convention (compact factory vs setup-stored instances)."""

    @pytest.mark.parametrize("policy", ["dots_saveable"])
    def test_funit_content_encoder(self, rng, policy):
        from imaginaire_tpu.models.generators.funit import (
            FUNITContentEncoder,
        )

        x = jnp.asarray(rng.randn(1, 32, 32, 3).astype(np.float32))
        trees, outs = [], []
        for pol in ("none", policy):
            enc = FUNITContentEncoder(num_downsamples=1, num_res_blocks=1,
                                      num_filters=4, remat=pol)
            variables = enc.init(jax.random.PRNGKey(0), x, training=False)
            trees.append(jax.tree_util.tree_structure(variables))
            outs.append(enc.apply(variables, x, training=False))
        assert trees[0] == trees[1]
        np.testing.assert_allclose(np.asarray(outs[0]),
                                   np.asarray(outs[1]), atol=1e-6)

    @pytest.mark.parametrize("policy", ["save_nothing"])
    def test_patch_discriminator(self, rng, policy):
        from imaginaire_tpu.models.discriminators.multires_patch import (
            NLayerPatchDiscriminator,
        )

        x = jnp.asarray(rng.randn(1, 32, 32, 3).astype(np.float32))
        trees, outs = [], []
        for pol in ("none", policy):
            d = NLayerPatchDiscriminator(num_filters=4, num_layers=2,
                                         remat=pol)
            variables = d.init(jax.random.PRNGKey(0), x, training=False)
            trees.append(jax.tree_util.tree_structure(variables))
            logits, _ = d.apply(variables, x, training=False)
            outs.append(logits)
        assert trees[0] == trees[1]
        np.testing.assert_allclose(np.asarray(outs[0]),
                                   np.asarray(outs[1]), atol=1e-6)

    def test_bad_value_same_message_everywhere(self, rng):
        """Family-local string checks are gone: a typo'd policy fails
        through resolve_policy with the shared message, at trace time."""
        from imaginaire_tpu.models.discriminators.multires_patch import (
            NLayerPatchDiscriminator,
        )
        from imaginaire_tpu.models.generators.funit import (
            FUNITContentEncoder,
        )

        x = jnp.asarray(rng.randn(1, 16, 16, 3).astype(np.float32))
        with pytest.raises(ValueError, match="gen.remat"):
            FUNITContentEncoder(num_filters=4, remat="block").init(
                jax.random.PRNGKey(0), x, training=False)
        with pytest.raises(ValueError, match="dis.remat"):
            NLayerPatchDiscriminator(num_filters=4, remat="offload").init(
                jax.random.PRNGKey(0), x, training=False)

    def test_vid2vid_call_block_dispatch(self, rng):
        """setup-based families store wrapped INSTANCES and dispatch via
        call_block: positional wrapper takes training first, plain
        blocks keep the kwarg path."""
        wrapped_cls = remat_block_cls(Res2dBlock, "blocks")
        import flax.linen as nn

        class M(nn.Module):
            def setup(self):
                self.blk = wrapped_cls(4, name="res")
                self.plain = Res2dBlock(4, name="res2")

            def __call__(self, x, training=False):
                x = call_block(self.blk, x, training=training)
                return call_block(self.plain, x, training=training)

        x = jnp.asarray(rng.randn(1, 8, 8, 4).astype(np.float32))
        m = M()
        variables = m.init(jax.random.PRNGKey(0), x, training=False)
        out = m.apply(variables, x, training=False)
        assert out.shape == x.shape
        assert np.isfinite(np.asarray(out)).all()

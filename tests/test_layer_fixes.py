"""Regression tests for layer-library fixes found in review:
grouped_modulated_conv2d kernel ordering, spectral-norm immutable apply,
prelu in subclass blocks, style threading, SPADE interpolation."""

import jax
import jax.numpy as jnp
import numpy as np

from imaginaire_tpu.layers import (
    Conv2dBlock,
    HyperConv2dBlock,
    MultiOutConv2dBlock,
    PartialConv2dBlock,
)
from imaginaire_tpu.layers.activation_norm import get_activation_norm_layer
from imaginaire_tpu.layers.hyper_ops import grouped_modulated_conv2d, per_sample_conv2d


def test_grouped_modulated_matches_per_sample(key, rng):
    b, h, w, cin, cout, k = 3, 8, 8, 4, 6, 3
    x = jnp.asarray(rng.randn(b, h, w, cin).astype(np.float32))
    kernels = jnp.asarray(rng.randn(b, k, k, cin, cout).astype(np.float32))
    got = grouped_modulated_conv2d(x, kernels, padding="SAME")
    want = per_sample_conv2d(x, kernels, padding="SAME")
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_grouped_modulated_stride_and_dilation(key, rng):
    b, h, w, cin, cout, k = 2, 8, 8, 3, 5, 3
    x = jnp.asarray(rng.randn(b, h, w, cin).astype(np.float32))
    kernels = jnp.asarray(rng.randn(b, k, k, cin, cout).astype(np.float32))
    got = grouped_modulated_conv2d(x, kernels, stride=2, padding="SAME", dilation=2)
    want = per_sample_conv2d(x, kernels, stride=2, padding="SAME", dilation=2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_spectral_apply_without_mutable_collection(key, rng):
    """apply(training=True) without mutable=['spectral'] must not crash —
    the u update is skipped, matching the docstring contract."""
    block = Conv2dBlock(out_channels=4, weight_norm_type="spectral")
    x = jnp.asarray(rng.randn(1, 8, 8, 3).astype(np.float32))
    variables = block.init(key, x)
    out = block.apply(variables, x, training=True)  # no mutable kwarg
    assert out.shape == (1, 8, 8, 4)
    # and WITH mutable the u vector does update
    out2, mut = block.apply(variables, x, training=True, mutable=["spectral"])
    u0 = jax.tree_util.tree_leaves(variables["spectral"])[0]
    u1 = jax.tree_util.tree_leaves(mut["spectral"])[0]
    assert not np.allclose(u0, u1)


def test_prelu_in_subclass_blocks(key, rng):
    x = jnp.asarray(rng.randn(1, 8, 8, 3).astype(np.float32))
    out, pre = MultiOutConv2dBlock(out_channels=4, nonlinearity="prelu").init_with_output(
        key, x)[0]
    assert out.shape == (1, 8, 8, 4)
    out2 = HyperConv2dBlock(out_channels=4, nonlinearity="prelu").init_with_output(
        key, x)[0]
    assert out2.shape == (1, 8, 8, 4)
    (out3, mask), _ = PartialConv2dBlock(out_channels=4, nonlinearity="prelu").init_with_output(
        key, x)
    assert out3.shape == (1, 8, 8, 4)


def test_multiout_weight_demod_style_threading(key, rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    style = jnp.asarray(rng.randn(2, 16).astype(np.float32))
    block = MultiOutConv2dBlock(out_channels=4, weight_norm_type="weight_demod")
    (out, pre), _ = block.init_with_output(key, x, style=style)
    assert out.shape == (2, 8, 8, 4)


def test_spade_interpolation_param(key, rng):
    x = jnp.asarray(rng.randn(1, 8, 8, 4).astype(np.float32))
    cond = jnp.asarray(rng.rand(1, 4, 4, 2).astype(np.float32))
    near = get_activation_norm_layer(
        "spatially_adaptive", {"interpolation": "nearest", "activation_norm_type": "instance"})
    bil = get_activation_norm_layer(
        "spatially_adaptive", {"interpolation": "bilinear", "activation_norm_type": "instance"})
    out_n, _ = near.init_with_output(key, x, cond)
    out_b, _ = bil.init_with_output(key, x, cond)
    # same params (same init key/structure), different interpolation → different output
    assert not np.allclose(np.asarray(out_n), np.asarray(out_b))

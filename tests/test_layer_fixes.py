"""Regression tests for layer-library fixes found in review:
grouped_modulated_conv2d kernel ordering, spectral-norm immutable apply,
prelu in subclass blocks, style threading, SPADE interpolation."""

import jax
import jax.numpy as jnp
import numpy as np

from imaginaire_tpu.layers import (
    Conv2dBlock,
    HyperConv2dBlock,
    MultiOutConv2dBlock,
    PartialConv2dBlock,
)
from imaginaire_tpu.layers.activation_norm import get_activation_norm_layer
from imaginaire_tpu.layers.hyper_ops import grouped_modulated_conv2d, per_sample_conv2d


def _reference_per_sample_conv(x, kernels, stride=1, padding="SAME",
                               dilation=1):
    """Independent oracle: an explicit python loop of single-sample
    convs — what the reference's per-sample F.conv2d loop computes
    (ref: layers/conv.py:545-590). Both production entry points
    (per_sample_conv2d and its grouped_modulated delegate) must match
    this, whatever lowering they use internally."""
    from jax import lax

    outs = []
    for i in range(x.shape[0]):
        outs.append(lax.conv_general_dilated(
            x[i:i + 1], kernels[i],
            window_strides=(stride, stride), padding=padding,
            rhs_dilation=(dilation, dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC")))
    return jnp.concatenate(outs, axis=0)


def test_grouped_modulated_matches_per_sample(key, rng):
    b, h, w, cin, cout, k = 3, 8, 8, 4, 6, 3
    x = jnp.asarray(rng.randn(b, h, w, cin).astype(np.float32))
    kernels = jnp.asarray(rng.randn(b, k, k, cin, cout).astype(np.float32))
    want = _reference_per_sample_conv(x, kernels, padding="SAME")
    for fn in (grouped_modulated_conv2d, per_sample_conv2d):
        got = fn(x, kernels, padding="SAME")
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_grouped_modulated_stride_and_dilation(key, rng):
    b, h, w, cin, cout, k = 2, 8, 8, 3, 5, 3
    x = jnp.asarray(rng.randn(b, h, w, cin).astype(np.float32))
    kernels = jnp.asarray(rng.randn(b, k, k, cin, cout).astype(np.float32))
    want = _reference_per_sample_conv(x, kernels, stride=2, padding="SAME",
                                      dilation=2)
    for fn in (grouped_modulated_conv2d, per_sample_conv2d):
        got = fn(x, kernels, stride=2, padding="SAME", dilation=2)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_per_sample_conv_sharded_island_matches(rng):
    """With a configured >1-device 'data' mesh the conv runs in a
    shard_map island — its output must equal the unsharded oracle (and
    the mesh must NEVER be auto-created by the layer op: peek, not
    get)."""
    from imaginaire_tpu.parallel import mesh as mesh_mod
    from imaginaire_tpu.parallel.mesh import create_mesh, set_mesh

    b, h, w, cin, cout, k = 8, 8, 8, 3, 5, 3
    x = jnp.asarray(rng.randn(b, h, w, cin).astype(np.float32))
    kernels = jnp.asarray(rng.randn(b, k, k, cin, cout).astype(np.float32))
    want = _reference_per_sample_conv(x, kernels)
    old = mesh_mod._GLOBAL_MESH
    try:
        set_mesh(None)
        # no configured mesh: the layer op must not install one
        got_plain = per_sample_conv2d(x, kernels)
        assert mesh_mod._GLOBAL_MESH is None
        np.testing.assert_allclose(got_plain, want, rtol=1e-4, atol=1e-4)
        set_mesh(create_mesh(("data",), (8,)))
        got_sharded = jax.jit(lambda a, b_: per_sample_conv2d(a, b_))(
            x, kernels)
        np.testing.assert_allclose(got_sharded, want, rtol=1e-4, atol=1e-4)
    finally:
        set_mesh(old)


def test_spectral_apply_without_mutable_collection(key, rng):
    """apply(training=True) without mutable=['spectral'] must not crash —
    the u update is skipped, matching the docstring contract."""
    block = Conv2dBlock(out_channels=4, weight_norm_type="spectral")
    x = jnp.asarray(rng.randn(1, 8, 8, 3).astype(np.float32))
    variables = block.init(key, x)
    out = block.apply(variables, x, training=True)  # no mutable kwarg
    assert out.shape == (1, 8, 8, 4)
    # and WITH mutable the u vector does update
    out2, mut = block.apply(variables, x, training=True, mutable=["spectral"])
    u0 = jax.tree_util.tree_leaves(variables["spectral"])[0]
    u1 = jax.tree_util.tree_leaves(mut["spectral"])[0]
    assert not np.allclose(u0, u1)


def test_prelu_in_subclass_blocks(key, rng):
    x = jnp.asarray(rng.randn(1, 8, 8, 3).astype(np.float32))
    out, pre = MultiOutConv2dBlock(out_channels=4, nonlinearity="prelu").init_with_output(
        key, x)[0]
    assert out.shape == (1, 8, 8, 4)
    out2 = HyperConv2dBlock(out_channels=4, nonlinearity="prelu").init_with_output(
        key, x)[0]
    assert out2.shape == (1, 8, 8, 4)
    (out3, mask), _ = PartialConv2dBlock(out_channels=4, nonlinearity="prelu").init_with_output(
        key, x)
    assert out3.shape == (1, 8, 8, 4)


def test_multiout_weight_demod_style_threading(key, rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    style = jnp.asarray(rng.randn(2, 16).astype(np.float32))
    block = MultiOutConv2dBlock(out_channels=4, weight_norm_type="weight_demod")
    (out, pre), _ = block.init_with_output(key, x, style=style)
    assert out.shape == (2, 8, 8, 4)


def test_spade_interpolation_param(key, rng):
    x = jnp.asarray(rng.randn(1, 8, 8, 4).astype(np.float32))
    cond = jnp.asarray(rng.rand(1, 4, 4, 2).astype(np.float32))
    near = get_activation_norm_layer(
        "spatially_adaptive", {"interpolation": "nearest", "activation_norm_type": "instance"})
    bil = get_activation_norm_layer(
        "spatially_adaptive", {"interpolation": "bilinear", "activation_norm_type": "instance"})
    out_n, _ = near.init_with_output(key, x, cond)
    out_b, _ = bil.init_with_output(key, x, cond)
    # same params (same init key/structure), different interpolation → different output
    assert not np.allclose(np.asarray(out_n), np.asarray(out_b))

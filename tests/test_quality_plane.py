"""Quality observability plane tests (ISSUE 18): the content-addressed
reference-feature store (roundtrip, multi-writer, quarantine), the EWMA
regression sentinel, the EvalPlane sweep schema, the check_run_health
quality gates, the report "## quality" section — plus the PRDC
hand-computed numpy reference the reference repo never had.
"""

import json
import os

import numpy as np
import pytest

from imaginaire_tpu import telemetry
from imaginaire_tpu.evaluation import (
    EvalPlane,
    FeatureStore,
    RegressionSentinel,
    evaluation_settings,
    extractor_id,
    make_patch_extractor,
    prdc_from_activations,
    reference_key,
)
from imaginaire_tpu.telemetry import core as tcore
from imaginaire_tpu.telemetry.report import render_report, summarize


@pytest.fixture
def tm_sandbox():
    old = tcore._TELEMETRY
    yield
    tcore._TELEMETRY.shutdown()
    tcore._TELEMETRY = old


@pytest.fixture
def rng():
    return np.random.RandomState(0)


# ------------------------------------------------------------------ PRDC
class TestPRDCReference:
    """prdc_from_activations against a brute-force loop implementation
    (Naeem et al. 2020 definitions, computed the slow obvious way)."""

    @staticmethod
    def _brute_force(real, fake, k):
        def knn_radius(x, i):
            d = sorted(np.linalg.norm(x[i] - x[j]) for j in range(len(x))
                       if j != i)
            return d[k - 1]

        r_real = [knn_radius(real, i) for i in range(len(real))]
        r_fake = [knn_radius(fake, j) for j in range(len(fake))]
        d = np.array([[np.linalg.norm(r - f) for f in fake] for r in real])
        precision = np.mean([(d[:, j] < r_real).any()
                             for j in range(len(fake))])
        recall = np.mean([(d[i, :] < r_fake).any()
                          for i in range(len(real))])
        density = np.mean([(d[:, j] < r_real).sum()
                           for j in range(len(fake))]) / k
        coverage = np.mean([d[i, :].min() < r_real[i]
                            for i in range(len(real))])
        return {"precision": float(precision), "recall": float(recall),
                "density": float(density), "coverage": float(coverage)}

    def test_matches_brute_force(self, rng):
        real = rng.randn(24, 5)
        fake = rng.randn(20, 5) * 1.3 + 0.4
        want = self._brute_force(real, fake, k=3)
        got = prdc_from_activations(real, fake, nearest_k=3)
        for name in ("precision", "recall", "density", "coverage"):
            assert got[name] == pytest.approx(want[name], abs=1e-12), name

    def test_hand_computed_fixture(self):
        """1-D points, k=1, small enough to verify by eye.

        real = [0, 1, 10]; fake = [0.4, 20].
        Real 1-NN radii: [1, 1, 9]. Fake 1-NN radii: [19.6, 19.6].
        fake 0.4 is inside real balls at 0 and 1 (|d|=0.4,0.6 < 1);
        fake 20 is inside none -> precision 1/2, density (2+0)/2/1 = 1.
        Every real point is within 19.6 of a fake -> recall 1.
        Real balls at 0 and 1 contain fake 0.4; the ball at 10
        (radius 9) contains neither fake (9.6, 10) -> coverage 2/3."""
        real = np.array([[0.0], [1.0], [10.0]])
        fake = np.array([[0.4], [20.0]])
        out = prdc_from_activations(real, fake, nearest_k=1)
        assert out["precision"] == pytest.approx(0.5)
        assert out["recall"] == pytest.approx(1.0)
        assert out["density"] == pytest.approx(1.0)
        assert out["coverage"] == pytest.approx(2.0 / 3.0)

    def test_identical_sets_degenerate(self):
        """real == fake with fewer points than the default k: the
        nearest_k clamp must evaluate (not crash) and every identity
        metric must saturate at 1. Density is NOT 1 even for identical
        sets (ball membership is strict <): with k clamped to 2, radii
        are [1, sqrt2, sqrt2] and the per-point membership counts are
        3, 1, 1 -> density (3+1+1)/3/2 = 5/6."""
        x = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
        out = prdc_from_activations(x, x.copy(), nearest_k=5)
        assert out["precision"] == pytest.approx(1.0)
        assert out["recall"] == pytest.approx(1.0)
        assert out["coverage"] == pytest.approx(1.0)
        assert out["density"] == pytest.approx(5.0 / 6.0)


# --------------------------------------------------------- feature store
class TestFeatureStore:
    def test_roundtrip_and_stats(self, tmp_path, rng):
        store = FeatureStore(str(tmp_path))
        key = reference_key("cityscapes", "inception-g2:w:1:2", "256x256")
        acts = rng.randn(10, 16).astype(np.float32)
        assert store.get(key) is None
        store.put(key, acts, dataset="cityscapes")
        got = store.get(key)
        np.testing.assert_array_equal(got, acts)
        s = store.stats()
        assert s["hits"] == 1 and s["misses"] == 1
        assert s["hit_rate"] == pytest.approx(0.5)

    def test_key_sensitivity(self):
        base = reference_key("ds", "ex", "256x256")
        assert reference_key("ds", "ex", "256x256") == base
        assert reference_key("ds2", "ex", "256x256") != base
        assert reference_key("ds", "ex2", "256x256") != base
        assert reference_key("ds", "ex", "128x128") != base
        assert reference_key("ds", "ex", "256x256", max_batches=4) != base
        assert reference_key("ds", "ex", (256, 256)) == base

    def test_multi_writer_last_commit_wins_atomically(self, tmp_path, rng):
        """Two writers racing the same key must both succeed and leave
        exactly one intact shard (atomic os.replace, no partial file).
        A second put of an existing key is a cheap no-op."""
        a, b = FeatureStore(str(tmp_path)), FeatureStore(str(tmp_path))
        key = reference_key("ds", "ex", "native")
        acts = rng.randn(4, 8).astype(np.float32)
        a.put(key, acts)
        b.put(key, acts + 1.0)  # existence-skip: first commit stands
        shard_dir = os.path.dirname(a.path(key))
        files = [f for f in os.listdir(shard_dir) if f.endswith(".npz")]
        assert len(files) == 1, files
        np.testing.assert_array_equal(a.get(key), acts)

    def test_quarantine_on_corrupt(self, tm_sandbox, tmp_path, rng):
        tm = telemetry.configure(enabled=True, sinks=[],
                                 flush_every_n_steps=0)
        store = FeatureStore(str(tmp_path))
        key = reference_key("ds", "ex", "native")
        store.put(key, rng.randn(4, 8).astype(np.float32))
        with open(store.path(key), "wb") as f:
            f.write(b"not a zipfile")
        assert store.get(key) is None  # quarantined, reads as a miss
        assert not os.path.exists(store.path(key))
        quarantined = [f for f in os.listdir(os.path.dirname(
            store.path(key))) if f.endswith(".corrupt")]
        assert len(quarantined) == 1, quarantined
        assert store.stats()["corrupt_shards"] == 1
        names = {e["name"] for e in tm._events}
        assert "eval/store_corrupt" in names
        # recompute path works again after quarantine
        store.put(key, rng.randn(4, 8).astype(np.float32))
        assert store.get(key) is not None

    def test_extractor_id_shapes(self, tmp_path):
        rid = extractor_id(random_init=True)
        assert "random-init" in rid
        wpath = tmp_path / "w.npz"
        wpath.write_bytes(b"x" * 37)
        wid = extractor_id(weights_path=str(wpath))
        assert "w.npz" in wid and ":37:" in wid

    def test_settings_defaults_and_parse(self):
        s = evaluation_settings(None)
        assert s["every_n_iter"] is None and s["store"] is True
        assert s["extractor"] == "inception"
        s2 = evaluation_settings({"evaluation": {
            "every_n_iter": 50, "extractor": "patch", "metrics": ["fid"],
            "regression_threshold": 0.3}})
        assert s2["every_n_iter"] == 50
        assert s2["extractor"] == "patch"
        assert s2["regression_threshold"] == pytest.approx(0.3)


# -------------------------------------------------------------- sentinel
class TestRegressionSentinel:
    def test_improving_series_never_fires(self):
        s = RegressionSentinel(threshold=0.05, consecutive=2)
        for v in [50.0, 40.0, 30.0, 25.0, 24.0]:
            assert s.observe(v) is None
        assert s.fired == 0

    def test_single_spike_does_not_fire(self):
        s = RegressionSentinel(threshold=0.2, consecutive=2, beta=0.5)
        assert s.observe(10.0) is None
        assert s.observe(20.0) is None  # breach 1 of 2
        assert s.observe(10.0) is None  # recovered: streak resets
        assert s.fired == 0

    def test_persistent_degradation_fires_once(self, tm_sandbox):
        """The leg_spade_eval numerics: [10, 20, 20, 20] with beta 0.5
        fires exactly at the second consecutive breach, then the EWMA
        adapts to the new plateau and the streak resets."""
        tm = telemetry.configure(enabled=True, sinks=[],
                                 flush_every_n_steps=0)
        s = RegressionSentinel(threshold=0.2, consecutive=2, beta=0.5)
        results = [s.observe(v, step=i)
                   for i, v in enumerate([10.0, 20.0, 20.0, 20.0])]
        assert results[0] is None and results[1] is None
        assert results[2] is not None and results[2]["streak"] == 2
        assert results[3] is None
        assert s.fired == 1
        metas = [e for e in tm._events if e["kind"] == "meta"
                 and e["name"] == "eval/regression"]
        assert len(metas) == 1 and metas[0]["metric"] == "fid"
        ctrs = [e for e in tm._events if e["kind"] == "counter"
                and e["name"] == "eval/regressions"]
        assert ctrs and ctrs[-1]["value"] == 1.0


# ----------------------------------------------------------- eval plane
def _synthetic_loader(rng, batches=3, bs=4, hw=16):
    return [{"images": rng.rand(bs, hw, hw, 3).astype(np.float32) * 2 - 1}
            for _ in range(batches)]


def _gen_fn(data):
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(data["images"])) * 0.5


class TestEvalPlane:
    def test_sweep_schema_and_store_warmup(self, tm_sandbox, tmp_path, rng):
        tm = telemetry.configure(enabled=True, sinks=[],
                                 flush_every_n_steps=0)
        plane = EvalPlane(cfg={"evaluation": {"extractor": "patch"}},
                          store_dir=str(tmp_path))
        loader = _synthetic_loader(rng)
        extractor = make_patch_extractor(grid=4)
        kwargs = dict(dataset_name="synth", resolution="16x16",
                      extractor_tag="patch-v1:g4")
        r1 = plane.run_sweep(loader, "images", "fake_images", extractor,
                             _gen_fn, step=10, **kwargs)
        r2 = plane.run_sweep(loader, "images", "fake_images", extractor,
                             _gen_fn, step=20, **kwargs)
        assert not r1["ref_cache_hit"] and r2["ref_cache_hit"]
        assert r1["fid"] == pytest.approx(r2["fid"], rel=1e-6)
        assert r1["fid"] > 0 and np.isfinite(r1["fid"])
        assert r2["sweep"] == 2
        assert r1["time_to_fid_ms"] > 0
        assert plane.store_stats()["hits"] == 1
        ctr = {}
        for e in tm._events:
            if e["kind"] == "counter":
                ctr.setdefault(e["name"], []).append(e["value"])
        for name in ("eval/fid", "eval/time_to_fid_ms", "eval/batches"):
            assert name in ctr, sorted(ctr)
        assert ctr["eval/ref_cache_hit"] == [0.0, 1.0]
        sweeps = [e for e in tm._events if e["kind"] == "meta"
                  and e["name"] == "eval/sweep"]
        assert len(sweeps) == 2 and sweeps[0]["dataset"] == "synth"

    def test_kid_metric_optional(self, tm_sandbox, tmp_path, rng):
        telemetry.configure(enabled=True, sinks=[], flush_every_n_steps=0)
        plane = EvalPlane(cfg={"evaluation": {"extractor": "patch"}},
                          store_dir=str(tmp_path))
        r = plane.run_sweep(_synthetic_loader(rng), "images",
                            "fake_images", make_patch_extractor(grid=4),
                            _gen_fn, metrics=["fid", "kid"],
                            extractor_tag="patch-v1:g4")
        assert "kid" in r and np.isfinite(r["kid"])


# ------------------------------------------------- gates + report render
def _quality_events(fids, regressions=0, hits=(0, 1, 1)):
    events = []
    for i, fid in enumerate(fids):
        step = (i + 1) * 100
        events.append({"kind": "counter", "name": "eval/fid",
                       "value": fid, "step": step, "t": 0.0})
        events.append({"kind": "counter", "name": "eval/time_to_fid_ms",
                       "value": 1000.0, "step": step, "t": 0.0})
        events.append({"kind": "counter", "name": "eval/ref_cache_hit",
                       "value": float(hits[i % len(hits)]), "step": step,
                       "t": 0.0})
        events.append({"kind": "meta", "name": "eval/sweep", "t": 0.0,
                       "sweep": i + 1, "step": step, "fid": fid})
    if regressions:
        events.append({"kind": "counter", "name": "eval/regressions",
                       "value": float(regressions), "step": step,
                       "t": 0.0})
        events.append({"kind": "meta", "name": "eval/regression",
                       "t": 0.0, "metric": "fid", "step": step,
                       "value": fids[-1], "baseline": fids[0],
                       "delta": 0.5, "threshold": 0.05, "streak": 2})
    return events


class TestQualityGates:
    def _check(self, events, **kw):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_run_health", os.path.join(
                os.path.dirname(__file__), "..", "scripts",
                "check_run_health.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.check_health(summarize(events), **kw)

    def test_gates_absent_counters_pass(self):
        # graph-gate idiom: a run that never evaluated passes untouched
        assert self._check([], max_fid=1.0,
                           max_quality_regressions=0) == []

    def test_max_fid_gate(self):
        events = _quality_events([30.0, 25.0, 40.0])
        assert self._check(events, max_fid=50.0) == []
        failures = self._check(events, max_fid=35.0)
        assert len(failures) == 1 and "40" in failures[0]

    def test_regression_gate(self):
        clean = _quality_events([30.0, 25.0, 24.0])
        assert self._check(clean, max_quality_regressions=0) == []
        bad = _quality_events([30.0, 45.0, 50.0], regressions=1)
        failures = self._check(bad, max_quality_regressions=0)
        assert len(failures) == 1 and "regression" in failures[0]
        assert self._check(bad, max_quality_regressions=1) == []

    def test_report_quality_section(self):
        events = _quality_events([30.0, 25.0, 40.0], regressions=1)
        s = summarize(events)
        q = s["quality"]
        assert q["present"] and q["sweep_count"] == 3
        assert q["fid_latest"] == pytest.approx(40.0)
        assert q["fid_best"] == pytest.approx(25.0)
        assert q["regressions"] == 1
        assert q["ref_cache_hits"] == 2
        text = render_report(events)
        assert "## quality" in text
        assert "!! quality regressions: 1" in text
        assert "| sweep |" in text

    def test_report_no_quality_section_when_absent(self):
        assert "## quality" not in render_report(
            [{"kind": "counter", "name": "loss/total", "value": 1.0,
              "step": 1, "t": 0.0}])


# ------------------------------------------------ instrumented activations
class TestInstrumentedActivations:
    def test_get_activations_spans_and_counter(self, tm_sandbox, rng):
        from imaginaire_tpu.evaluation.common import get_activations

        tm = telemetry.configure(enabled=True, sinks=[],
                                 flush_every_n_steps=0)
        acts = get_activations(_synthetic_loader(rng, batches=2), "images",
                               "fake_images", make_patch_extractor(grid=4),
                               generator_fn=_gen_fn)
        assert acts.shape[0] == 8
        spans = [e["name"] for e in tm._events if e["kind"] == "span"]
        assert spans.count("eval_extract") == 2
        assert spans.count("eval_generate") == 2
        batches = [e for e in tm._events if e["kind"] == "counter"
                   and e["name"] == "eval/batches"]
        assert batches and batches[-1]["value"] == 2.0

"""Elastic pods (ISSUE 11): the resize machinery in isolation.

Covers the pieces the 3->2->3 chaos drill (dryrun leg
``spade_elastic``) exercises end-to-end: ``ResizePlan`` consensus
derivation (shrink votes over the KV store, deterministic grow plans),
``fit_mesh_shape`` re-derivation across world sizes, the
block-contiguous loader split's world-size invariance, barrier-epoch
negotiation on (re)join, orphan runstate sidecars after a shrink, the
joiner rendezvous files, and the health gate's ``--max-resizes``
budget. Everything runs single-process against the same fake
coordination-service KV client as ``test_cluster.py``."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from imaginaire_tpu.config import AttrDict
from imaginaire_tpu.resilience import cluster, elastic
from imaginaire_tpu.resilience.cluster import ClusterDesyncError
from imaginaire_tpu.resilience.elastic import (
    ElasticCoordinator,
    ElasticResize,
    ResizePlan,
)


class FakeBarrierTimeout(Exception):
    pass


class FakeClient:
    """KV + barrier surface of the distributed-runtime client (same
    shape as the one in test_cluster.py)."""

    def __init__(self, n, present=None):
        self.n = n
        self.present = set(range(n)) if present is None else set(present)
        self.kv = {}
        self.barrier_calls = []

    def key_value_set(self, key, value, allow_overwrite=False):
        if not allow_overwrite and key in self.kv:
            raise RuntimeError(f"key exists: {key}")
        self.kv[key] = value

    def key_value_dir_get(self, prefix):
        return sorted((k, v) for k, v in self.kv.items()
                      if k.startswith(prefix))

    def key_value_delete(self, key):
        self.kv.pop(key, None)

    def wait_at_barrier(self, barrier_id, timeout_ms, process_ids=None):
        self.barrier_calls.append(barrier_id)
        if self.present != set(range(self.n)):
            raise FakeBarrierTimeout(
                f"DEADLINE_EXCEEDED: Barrier timed out. Id: "
                f"{barrier_id}")


@pytest.fixture(autouse=True)
def _reset_cluster():
    cluster._BARRIER_EPOCH.clear()
    yield
    cluster.set_client_for_testing(None)
    cluster._SETTINGS = None
    cluster._BARRIER_EPOCH.clear()


def _elastic_cfg(**overrides):
    ecfg = dict({"enabled": True, "min_world_size": 2,
                 "resize_timeout_s": 0.3}, **overrides)
    return AttrDict({"resilience": {"elastic": ecfg}})


def _coordinator(tmp_path=None, env=None, **overrides):
    if env is not None:
        env.setdefault("IMAGINAIRE_ELASTIC_BASE_COORDINATOR",
                       "127.0.0.1:6000")
        for key, value in env.items():
            os.environ[key] = value
    co = ElasticCoordinator(
        _elastic_cfg(**overrides),
        logdir=str(tmp_path) if tmp_path is not None else None)
    return co


@pytest.fixture
def base_env(monkeypatch):
    monkeypatch.setenv("IMAGINAIRE_ELASTIC_BASE_COORDINATOR",
                       "127.0.0.1:6000")
    monkeypatch.delenv("IMAGINAIRE_ELASTIC_GENERATION", raising=False)


# ------------------------------------------------------------ ResizePlan


class TestResizePlan:
    def test_json_round_trip(self):
        plan = ResizePlan(
            2, ["p0", "p1", "rejoin-p2"], "127.0.0.1:6034",
            iteration=5, epoch=1, mesh_axes=["data", "model"],
            mesh_shape=[6, 1], barrier_epochs={"psync": 7},
            reason="grow", old_world=2, old_mesh_shape=[6, 1])
        back = ResizePlan.from_json(plan.to_json())
        assert back.generation == 2
        assert back.members == ["p0", "p1", "rejoin-p2"]
        assert back.coordinator == "127.0.0.1:6034"
        assert back.iteration == 5 and back.epoch == 1
        assert back.mesh_axes == ["data", "model"]
        assert back.mesh_shape == [6, 1]
        assert back.barrier_epochs == {"psync": 7}
        assert back.reason == "grow"
        assert back.old_world == 2 and back.old_mesh_shape == [6, 1]

    def test_member_identity(self):
        plan = ResizePlan(1, ["p0", "p2"], "h:1")
        assert plan.world_size == 2
        # a member's NEW process id is its index — survivor p2 becomes
        # process 1 of the shrunken world, the old master stays master
        assert plan.process_id_of("p0") == 0
        assert plan.process_id_of("p2") == 1
        assert plan.process_id_of("p1") is None

    def test_defaults_round_trip(self):
        back = ResizePlan.from_json(ResizePlan(1, ["p0"], "h:1").to_json())
        assert back.mesh_shape is None and back.mesh_axes is None
        assert back.iteration == -1 and back.reason == "shrink"


# ------------------------------------------------- fit_mesh_shape rules


class TestFitMeshShape:
    def _cfg(self, shape, axes=("data", "model"), **extra):
        return AttrDict({"parallel": dict({"mesh_shape": list(shape),
                                           "axes": list(axes)}, **extra)})

    def test_constant_mesh_survives_overprovision(self):
        from imaginaire_tpu.parallel.mesh import fit_mesh_shape

        # the drill's invariant: [6, 1] fits BOTH 3 procs x 3 devices
        # (9, one idle each) and 2 procs x 3 devices (6, none idle) —
        # the logical mesh, hence the math, never changes
        for total in (9, 6):
            axes, dims = fit_mesh_shape(self._cfg([6, 1]), total)
            assert tuple(axes) == ("data", "model")
            assert list(dims) == [6, 1]

    def test_data_axis_shrinks_to_surviving_world(self):
        from imaginaire_tpu.parallel.mesh import fit_mesh_shape

        axes, dims = fit_mesh_shape(self._cfg([4, 1]), 3)
        assert list(dims) == [3, 1]

    def test_model_axis_collapse_warns(self, caplog):
        from imaginaire_tpu.parallel.mesh import fit_mesh_shape

        # (2, 2) on 2 surviving devices: ties collapse toward pure DP,
        # the dead model axis warns (its partition rules go inert)
        with caplog.at_level("WARNING"):
            axes, dims = fit_mesh_shape(self._cfg([2, 2]), 2)
        assert list(dims) == [2, 1]
        assert any("model" in r.message for r in caplog.records)

    def test_no_configured_shape_is_unconstrained(self):
        from imaginaire_tpu.parallel.mesh import fit_mesh_shape

        axes, dims = fit_mesh_shape(AttrDict({}), 5)
        assert dims is None


# ------------------------------------------------- shrink consensus


class TestAgreeSurvivors:
    def test_all_votes_collected(self):
        client = FakeClient(3)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=3)
        # p1's vote is already in the KV store when p0 arrives
        client.kv["elastic/shrink/1/p1"] = json.dumps(
            {"it": 7, "ep": 0, "tok": "p1"})
        votes = cluster.agree_survivors(
            "shrink", 1, {"it": 9, "ep": 0, "tok": "p0"}, [0, 1],
            timeout_s=2.0)
        assert sorted(votes) == [0, 1]
        assert votes[1]["it"] == 7
        # own vote was published for the peer's poll
        assert "elastic/shrink/1/p0" in client.kv

    def test_timeout_names_missing_survivor(self):
        client = FakeClient(3)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=3)
        with pytest.raises(ClusterDesyncError) as exc:
            cluster.agree_survivors("shrink", 1, {"it": 9}, [0, 1],
                                    timeout_s=0.15, poll_s=0.02)
        assert "[1]" in str(exc.value)

    def test_single_survivor_short_circuits(self):
        votes = cluster.agree_survivors("shrink", 1, {"it": 3}, [0],
                                        timeout_s=0.1)
        assert votes == {0: {"it": 3}}


class TestCoordinatorShrink:
    def test_can_shrink_gates(self, base_env):
        client = FakeClient(3)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=3)
        co = _coordinator()
        assert co.can_shrink([2]) is True
        # the master carries the KV store: its death ends the pod
        assert co.can_shrink([0, 2]) is False
        # two deaths of three would leave the world below min_world_size=2
        assert co.can_shrink([1, 2]) is False
        assert co.can_shrink([]) is False
        off = ElasticCoordinator(
            AttrDict({"resilience": {"elastic": {"enabled": False}}}))
        assert off.can_shrink([2]) is False

    def test_port_schedule_is_deterministic(self, base_env):
        co = _coordinator()
        stride = co.settings["port_stride"]
        assert co.coordinator_for(0) == "127.0.0.1:6000"
        assert co.coordinator_for(1) == f"127.0.0.1:{6000 + stride}"
        assert co.coordinator_for(3) == f"127.0.0.1:{6000 + 3 * stride}"

    def test_missing_base_coordinator_raises(self, monkeypatch):
        monkeypatch.delenv("IMAGINAIRE_ELASTIC_BASE_COORDINATOR",
                           raising=False)
        monkeypatch.delenv("IMAGINAIRE_DIST_COORDINATOR", raising=False)
        co = ElasticCoordinator(_elastic_cfg())
        with pytest.raises(RuntimeError, match="coordinator"):
            co.coordinator_for(1)

    def test_plan_shrink_derivation(self, base_env, tmp_path):
        client = FakeClient(3)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=3)
        client.kv["elastic/shrink/1/p1"] = json.dumps(
            {"it": 4, "ep": 0, "tok": "p1"})
        cluster._BARRIER_EPOCH["psync"] = 5
        co = _coordinator(tmp_path)
        plan = co.plan_shrink([2], iteration=6, epoch=0)
        assert plan.generation == 1
        assert plan.members == ["p0", "p1"]
        stride = co.settings["port_stride"]
        assert plan.coordinator == f"127.0.0.1:{6000 + stride}"
        # the agreed iteration is the MINIMUM valid vote — the
        # checkpoint every survivor provably has
        assert plan.iteration == 4
        assert plan.reason == "shrink" and plan.old_world == 3
        assert plan.barrier_epochs["psync"] == 5
        # p0 is the min survivor: it published the topology file the
        # future joiners rendezvous on
        topo = ResizePlan.from_json(
            open(co.topology_path()).read())
        assert topo.members == plan.members
        assert topo.generation == 1


# ------------------------------------------------------------- grow


class TestCoordinatorGrow:
    def test_join_request_round_trip(self, base_env, tmp_path):
        co = _coordinator(tmp_path)
        assert co.check_join_requests() == []
        elastic.request_join(tmp_path, "rejoin-p2")
        elastic.request_join(tmp_path, "aaa")
        assert co.check_join_requests() == ["aaa", "rejoin-p2"]
        co.consume_join_requests(["aaa", "rejoin-p2"])
        assert co.check_join_requests() == []

    def test_announce_and_poll_grow(self, base_env, tmp_path):
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        co = _coordinator(tmp_path)
        rec = co.announce_grow(12, ["rejoin-p2"])
        assert rec == {"target": 12, "joiners": ["rejoin-p2"],
                       "generation": 1}
        # re-announcing the same joiner set is a no-op (one decision
        # per sync step, not one per poll)
        assert co.announce_grow(14, ["rejoin-p2"]) is None
        got = co.poll_grow()
        assert got["target"] == 12 and got["joiners"] == ["rejoin-p2"]

    def test_plan_grow_membership(self, base_env, tmp_path):
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        cluster._BARRIER_EPOCH["ckpt_enter"] = 3
        co = _coordinator(tmp_path)
        plan = co.plan_grow(["zz-nonce", "aa-nonce"], iteration=12,
                            epoch=2)
        # survivors keep their ids; joiners take the NEW tail ids in
        # sorted-nonce order — every member derives this identically
        assert plan.members == ["p0", "p1", "aa-nonce", "zz-nonce"]
        assert plan.process_id_of("aa-nonce") == 2
        assert plan.generation == 1 and plan.reason == "grow"
        assert plan.iteration == 12 and plan.epoch == 2
        assert plan.barrier_epochs["ckpt_enter"] == 3

    def test_wait_for_join_env_contract(self, base_env, tmp_path,
                                        monkeypatch):
        for var in ("IMAGINAIRE_DIST_COORDINATOR",
                    "IMAGINAIRE_DIST_NUM_PROCESSES",
                    "IMAGINAIRE_DIST_PROCESS_ID", "IMAGINAIRE_ELASTIC"):
            monkeypatch.setenv(var, "sentinel")
        monkeypatch.setenv("IMAGINAIRE_ELASTIC_GENERATION", "0")
        co = _coordinator(tmp_path)
        plan = ResizePlan(2, ["p0", "p1", "rejoin-p2"],
                          "127.0.0.1:6034", iteration=5,
                          barrier_epochs={"psync": 9}, reason="grow")
        co.publish_topology(plan)
        got = elastic.wait_for_join(tmp_path, "rejoin-p2",
                                    timeout_s=2.0, poll_s=0.01)
        assert got.generation == 2
        assert os.environ["IMAGINAIRE_DIST_PROCESS_ID"] == "2"
        assert os.environ["IMAGINAIRE_DIST_NUM_PROCESSES"] == "3"
        assert os.environ["IMAGINAIRE_DIST_COORDINATOR"] == \
            "127.0.0.1:6034"
        assert os.environ["IMAGINAIRE_ELASTIC"] == "1"
        assert os.environ["IMAGINAIRE_ELASTIC_GENERATION"] == "2"

    def test_wait_for_join_times_out_unlisted(self, base_env, tmp_path):
        co = _coordinator(tmp_path)
        co.publish_topology(ResizePlan(1, ["p0", "p1"], "h:1"))
        with pytest.raises(TimeoutError, match="not granted"):
            elastic.wait_for_join(tmp_path, "somebody-else",
                                  timeout_s=0.1, poll_s=0.02)


# ------------------------------------------------- barrier negotiation


class TestBarrierEpochNegotiation:
    def test_export_snapshots_counters(self):
        cluster._BARRIER_EPOCH.update({"psync": 4, "ckpt_enter": 2})
        snap = cluster.export_barrier_epochs()
        assert snap == {"psync": 4, "ckpt_enter": 2}
        snap["psync"] = 99  # a copy, not the live table
        assert cluster._BARRIER_EPOCH["psync"] == 4

    def test_adopt_is_max_merge(self):
        cluster._BARRIER_EPOCH.update({"psync": 4})
        # a joiner fast-forwards to the cluster snapshot...
        cluster.adopt_barrier_epochs({"psync": 9, "ckpt_enter": 3})
        assert cluster._BARRIER_EPOCH["psync"] == 9
        assert cluster._BARRIER_EPOCH["ckpt_enter"] == 3
        # ...but NEVER rewinds: a reused barrier id is poison
        cluster.adopt_barrier_epochs({"psync": 2})
        assert cluster._BARRIER_EPOCH["psync"] == 9

    def test_adopt_survives_plan_json_keys(self):
        # barrier epochs ride ResizePlan JSON — keys come back as str
        plan = ResizePlan.from_json(ResizePlan(
            1, ["p0"], "h:1", barrier_epochs={"psync": 6}).to_json())
        cluster.adopt_barrier_epochs(plan.barrier_epochs)
        assert cluster._BARRIER_EPOCH["psync"] == 6


# ---------------------------------------- block-contiguous loader split


class _IndexDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return {"x": np.asarray([i])}


class TestLoaderBlockSplit:
    def _orders(self, world, n=24, g=6, shuffle=True):
        from imaginaire_tpu.data import loader as loader_mod

        per_host = []
        for rank in (range(world)):
            dl = loader_mod.DataLoader(_IndexDataset(n), batch_size=1,
                                       shuffle=shuffle, seed=3,
                                       global_batch_size=g)
            dl.set_epoch(1)
            loader_mod.get_world_size = lambda: world
            loader_mod.get_rank = lambda r=rank: r
            try:
                per_host.append(dl._order())
            finally:
                from imaginaire_tpu.parallel.mesh import (
                    get_rank,
                    get_world_size,
                )

                loader_mod.get_rank = get_rank
                loader_mod.get_world_size = get_world_size
        return per_host

    def _global_batches(self, world, **kw):
        per_host = self._orders(world, **kw)
        share = per_host[0].size // (24 // 6)
        batches = []
        for k in range(24 // 6):
            rows = [h[k * share:(k + 1) * share] for h in per_host]
            batches.append(np.concatenate(rows))
        return batches

    def test_global_batch_world_invariant(self):
        # THE elastic bit-exactness property: global batch k is the
        # same rows in the same mesh order at world 3, 2 and 1
        b3 = self._global_batches(3)
        b2 = self._global_batches(2)
        b1 = self._global_batches(1)
        for k in range(len(b3)):
            assert np.array_equal(b3[k], b2[k])
            assert np.array_equal(b3[k], b1[k])

    def test_per_host_batch_follows_live_world(self):
        from imaginaire_tpu.data import loader as loader_mod

        dl = loader_mod.DataLoader(_IndexDataset(24), batch_size=1,
                                   global_batch_size=6)
        for world, share in ((3, 2), (2, 3), (1, 6)):
            loader_mod.get_world_size = lambda w=world: w
            try:
                assert dl.batch_size == share
                # epoch length is measured in GLOBAL batches — also
                # world-invariant
                assert len(dl) == 4
            finally:
                from imaginaire_tpu.parallel.mesh import get_world_size

                loader_mod.get_world_size = get_world_size

    def test_indivisible_world_floors_and_warns(self, caplog):
        from imaginaire_tpu.data import loader as loader_mod

        dl = loader_mod.DataLoader(_IndexDataset(24), batch_size=1,
                                   global_batch_size=6)
        loader_mod.get_world_size = lambda: 4
        try:
            with caplog.at_level("WARNING"):
                assert dl.batch_size == 1
                assert dl.batch_size == 1  # warned once per world
        finally:
            from imaginaire_tpu.parallel.mesh import get_world_size

            loader_mod.get_world_size = get_world_size
        warns = [r for r in caplog.records
                 if "not divisible" in r.message]
        assert len(warns) == 1


# ------------------------------------------------ orphan runstate files


class TestOrphanSidecars:
    def _mk(self, tmp_path, indices, legacy=True):
        ck = tmp_path / "epoch_00000_iteration_000000004_checkpoint"
        ck.mkdir()
        (ck / "data").write_bytes(b"x")
        if legacy:
            (tmp_path / (ck.name + ".runstate.json")).write_text(
                json.dumps({"iteration": 4, "epoch": 0}))
        for i in indices:
            (tmp_path / (ck.name + f".runstate.p{i}.json")).write_text(
                json.dumps({"iteration": 4, "epoch": 0, "p": i}))
        return str(ck)

    def test_runstate_index(self):
        from imaginaire_tpu.resilience.integrity import runstate_index

        # the legacy master sidecar has no index suffix — it is never
        # an orphan candidate
        assert runstate_index("x_checkpoint.runstate.json") is None
        assert runstate_index("x_checkpoint.runstate.p3.json") == 3
        assert runstate_index("x_checkpoint.integrity.json") is None

    def test_orphans_against_explicit_world(self, tmp_path):
        from imaginaire_tpu.resilience.integrity import orphan_sidecars

        ck = self._mk(tmp_path, [1, 2, 5])
        orphans = orphan_sidecars(ck, world_size=3)
        assert [os.path.basename(o) for o in orphans] == [
            "epoch_00000_iteration_000000004_checkpoint"
            ".runstate.p5.json"]
        assert orphan_sidecars(ck, world_size=6) == []

    def test_read_runstate_warns_but_reads(self, tmp_path, caplog):
        from imaginaire_tpu.resilience.runstate import read_runstate

        ck = self._mk(tmp_path, [7])
        with caplog.at_level("WARNING"):
            rec = read_runstate(ck)
        # the shrink leftover did not break resume — own record wins
        assert rec["iteration"] == 4
        assert any("orphan" in r.message for r in caplog.records)


# ------------------------------------------- drain split / guard reset


class TestDrainSplit:
    def test_return_flagged_identifies_leavers(self):
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        client.kv["psync/5/p1"] = "1"
        # survivors (p0) learn WHICH host is leaving — the elastic
        # drain split keys off exactly this list
        flag, flagged = cluster.coordinate_preemption(
            5, False, timeout_s=5, return_flagged=True)
        assert flag is True and flagged == [1]

    def test_return_flagged_single_process(self):
        flag, flagged = cluster.coordinate_preemption(
            1, True, return_flagged=True)
        assert flag is True and flagged == [0]
        flag, flagged = cluster.coordinate_preemption(
            1, False, return_flagged=True)
        assert flag is False and flagged == []

    def test_guard_reset_clears_drain(self):
        from imaginaire_tpu.resilience.preemption import PreemptionGuard

        guard = PreemptionGuard(deadline_s=0.0)
        guard._triggered.set()
        guard.signum = 15
        assert guard.triggered
        # the survivors committed the leaver's emergency checkpoint and
        # keep training — a sticky flag would re-enter the drain at
        # every later vote
        guard.reset()
        assert not guard.triggered and guard.signum is None


# ---------------------------------------------- telemetry + health gate


_STEP = {"kind": "counter", "name": "perf/imgs_per_sec", "value": 1.0,
         "step": 1, "t": 0.0}


def _resize_events(n, world_from=3, world_to=2):
    events = []
    for g in range(1, n + 1):
        events.append({"kind": "meta", "name": "elastic/resize",
                       "generation": g, "reason": "shrink",
                       "old_world": world_from, "new_world": world_to,
                       "iteration": 2 * g, "downtime_ms": 1500.0,
                       "t": float(g)})
        events.append({"kind": "counter",
                       "name": "elastic/resizes",
                       "value": float(g), "step": 2 * g, "t": float(g)})
        events.append({"kind": "counter",
                       "name": "elastic/downtime_ms",
                       "value": 1500.0 * g, "step": 2 * g,
                       "t": float(g)})
    return events


def _write_jsonl(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


class TestResizeTelemetry:
    def test_summarize_collects_resizes(self, tmp_path):
        from imaginaire_tpu.telemetry.report import load_events, summarize

        path = tmp_path / "telemetry.jsonl"
        _write_jsonl(path, [_STEP] + _resize_events(2))
        s = summarize(load_events(str(path)))
        res = s["resilience"]
        # counters are latest-value-as-total: 2 resizes, cumulative
        # downtime — and every resize event is kept (meta dicts are
        # last-wins, the list is not)
        assert res["elastic_resizes"] == 2
        assert res["resize_downtime_ms"] == pytest.approx(3000.0)
        assert len(res["resize_events"]) == 2
        assert res["resize_events"][0]["generation"] == 1


class TestElasticGate:
    def _gate(self, rundir, *extra):
        script = os.path.join(os.path.dirname(__file__), "..",
                              "scripts", "check_run_health.py")
        return subprocess.run(
            [sys.executable, script, str(rundir), *extra],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))

    def test_resizes_within_budget_pass(self, tmp_path):
        _write_jsonl(tmp_path / "telemetry.jsonl",
                     [_STEP] + _resize_events(2))
        r = self._gate(tmp_path, "--max-resizes", "2")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_resizes_over_budget_fail(self, tmp_path):
        _write_jsonl(tmp_path / "telemetry.jsonl",
                     [_STEP] + _resize_events(2))
        r = self._gate(tmp_path, "--max-resizes", "1")
        assert r.returncode != 0
        assert "elastic" in r.stdout

    def test_no_budget_ignores_resizes(self, tmp_path):
        _write_jsonl(tmp_path / "telemetry.jsonl",
                     [_STEP] + _resize_events(3))
        r = self._gate(tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_hosts_mode_accepts_resized_pod(self, tmp_path):
        # after a 3->2 shrink only p0/p1 keep writing — the per-host
        # sweep must treat the recorded resize as the explanation for
        # p2's silence, not a failure
        _write_jsonl(tmp_path / "telemetry.jsonl.p0",
                     [_STEP] + _resize_events(1))
        _write_jsonl(tmp_path / "telemetry.jsonl.p1", [_STEP])
        _write_jsonl(tmp_path / "telemetry.jsonl.p2", [_STEP])
        r = self._gate(tmp_path, "--hosts", "--expect-hosts", "3",
                       "--max-resizes", "1")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_min_world_size_gate(self, tmp_path):
        # a 3->2 shrink is fine at --min-world-size 2 and a failure
        # at --min-world-size 3 (the pod dipped below the floor)
        _write_jsonl(tmp_path / "telemetry.jsonl",
                     [_STEP] + _resize_events(1))
        ok = self._gate(tmp_path, "--min-world-size", "2")
        assert ok.returncode == 0, ok.stdout + ok.stderr
        bad = self._gate(tmp_path, "--min-world-size", "3")
        assert bad.returncode != 0
        assert "world" in bad.stdout


# ------------------------------------------------- redistribution plan


class _ShardedLeaf:
    """A leaf whose sharding spans processes (a survivor only owns its
    shard) — must route via the checkpoint."""

    class _Sharding:
        is_fully_replicated = False

    def __init__(self, shape, dtype=np.float32):
        self._a = np.zeros(shape, dtype)
        self.sharding = self._Sharding()

    @property
    def size(self):
        return self._a.size

    @property
    def dtype(self):
        return self._a.dtype


def _plan(iteration=5, world=2):
    return ResizePlan(1, [f"p{i}" for i in range(world)],
                      "127.0.0.1:6017", iteration=iteration,
                      reason="shrink", old_world=world + 1)


class TestRedistributionPlanner:
    def _state(self):
        rng = np.random.RandomState(0)
        return {
            "vars_G": {"params": rng.rand(4, 3).astype(np.float32)},
            "opt_G": {"mu": rng.rand(4, 3).astype(np.float32),
                      "nu": rng.rand(4, 3).astype(np.float32)},
            "ema_G": {"w": rng.rand(2, 5).astype(np.float32)},
        }

    def test_byte_accounting_matches_state_bytes_report(self):
        from imaginaire_tpu.parallel.partition import state_bytes_report

        state = self._state()
        rp = elastic.RedistributionPlanner(_plan(iteration=5), 5, state)
        report = state_bytes_report(state)
        # the planner's total over the SAME subtrees equals the
        # partition ledger's global_bytes — one accounting, two views
        for key, rec in report.items():
            sub = elastic.RedistributionPlanner(
                _plan(iteration=5), 5, state[key])
            assert sub.total_bytes == rec["global_bytes"], key
        total = sum(v.size * v.dtype.itemsize
                    for part in state.values()
                    for v in part.values())
        assert rp.total_bytes == total

    def test_live_match_routes_gather(self):
        state = self._state()
        rp = elastic.RedistributionPlanner(_plan(iteration=5), 5, state)
        assert rp.all_gather
        assert rp.checkpoint_bytes == 0
        assert rp.route_counts() == {"gather": 4, "checkpoint": 0}

    def test_iteration_mismatch_routes_checkpoint(self):
        # a heartbeat-staleness shrink resumes from the LAST checkpoint
        # (plan.iteration -1): live leaves are ahead of it — carrying
        # them would resume from unagreed state
        state = self._state()
        rp = elastic.RedistributionPlanner(_plan(iteration=-1), 5, state)
        assert not rp.all_gather
        assert rp.gather_bytes == 0
        assert rp.route_counts()["checkpoint"] == 4

    def test_cross_process_shard_routes_checkpoint(self):
        state = self._state()
        state["opt_G"]["mu"] = _ShardedLeaf((4, 3))
        rp = elastic.RedistributionPlanner(_plan(iteration=5), 5, state)
        assert not rp.all_gather
        counts = rp.route_counts()
        assert counts == {"gather": 3, "checkpoint": 1}
        assert rp.checkpoint_bytes == 4 * 3 * 4

    def test_empty_state_never_all_gather(self):
        # a joiner has NO live state: nothing to carry, everything
        # restores from the checkpoint
        rp = elastic.RedistributionPlanner(_plan(iteration=5), 5, None)
        assert not rp.all_gather
        assert rp.total_bytes == 0

    def test_snapshot_owns_copies(self):
        state = self._state()
        rp = elastic.RedistributionPlanner(_plan(iteration=5), 5, state)
        carry = rp.snapshot(state)
        assert len(carry) == 4
        key = next(k for k in carry if "mu" in k)
        state["opt_G"]["mu"][:] = -1.0
        assert not np.any(carry[key] == -1.0)  # owned, not a view

    def test_summary_shape(self):
        state = self._state()
        state["ema_G"]["w"] = _ShardedLeaf((2, 5))
        rp = elastic.RedistributionPlanner(_plan(iteration=5), 5, state)
        s = rp.summary()
        assert s["redistributed_bytes"] == rp.total_bytes
        assert s["gather_bytes"] + s["checkpoint_bytes"] == \
            s["redistributed_bytes"]
        assert s["gather_leaves"] == 3 and s["checkpoint_leaves"] == 1

    def test_record_resize_carries_redistribution(self, tmp_path):
        from imaginaire_tpu import telemetry
        from imaginaire_tpu.telemetry import core as tcore

        co = _coordinator(tmp_path, env={})
        co.resizes = 1
        old = tcore._TELEMETRY
        tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                                 sinks=["jsonl"], flush_every_n_steps=0)
        try:
            co.record_resize(_plan(), 1234.5, {"reinit_ms": 200.0},
                             redistribution={"redistributed_bytes": 640,
                                             "gather_bytes": 640,
                                             "checkpoint_bytes": 0,
                                             "gather_leaves": 5,
                                             "checkpoint_leaves": 0})
            tm.shutdown()
        finally:
            tcore._TELEMETRY = old
        events = [json.loads(line) for line in
                  open(os.path.join(tmp_path, "telemetry.jsonl"))]
        meta = [e for e in events if e.get("name") == "elastic/resize"]
        assert meta and meta[0]["redistribution"][
            "redistributed_bytes"] == 640
        counters = {e["name"]: e["value"] for e in events
                    if e.get("kind") == "counter"}
        assert counters["elastic/redistributed_bytes"] == 640


class TestElasticityReport:
    def test_report_has_elasticity_section(self, tmp_path):
        from imaginaire_tpu.telemetry.report import render_report

        events = [_STEP] + _resize_events(2)
        events[1]["redistribution"] = {
            "redistributed_bytes": 2048, "gather_bytes": 0,
            "checkpoint_bytes": 2048, "gather_leaves": 0,
            "checkpoint_leaves": 7}
        events.append({"kind": "counter",
                       "name": "elastic/redistributed_bytes",
                       "value": 2048.0, "step": 2, "t": 2.0})
        path = tmp_path / "telemetry.jsonl"
        _write_jsonl(path, events)
        text = render_report(str(path))
        assert "## elasticity" in text
        assert "resizes: 2" in text
        assert "redistributed state bytes" in text
        assert "via checkpoint reshard" in text


# ----------------------------------------------- runstate epoch keying


class TestRunstateEpochKeying:
    def test_path_is_epoch_scoped(self):
        from imaginaire_tpu.resilience.runstate import runstate_path

        assert runstate_path("/x/ck", 0, epoch=0) == \
            "/x/ck.runstate.json"
        assert runstate_path("/x/ck", 2, epoch=0) == \
            "/x/ck.runstate.p2.json"
        assert runstate_path("/x/ck", 0, epoch=1) == \
            "/x/ck.runstate.e1.p0.json"
        assert runstate_path("/x/ck", 2, epoch=3) == \
            "/x/ck.runstate.e3.p2.json"

    def test_master_dual_writes_at_nonzero_epoch(self, tmp_path,
                                                 monkeypatch):
        from imaginaire_tpu.resilience import runstate

        monkeypatch.setattr(
            "imaginaire_tpu.parallel.mesh.get_rank", lambda: 0)
        cluster.set_membership_epoch(1)
        try:
            ck = str(tmp_path / "ck")
            rs = runstate.build_runstate(2, 7, 3)
            runstate.write_runstate(ck, rs)
        finally:
            cluster.set_membership_epoch(None)
        # the epoch-keyed sidecar AND the legacy cluster-truth copy
        assert os.path.exists(ck + ".runstate.e1.p0.json")
        assert os.path.exists(ck + ".runstate.json")

    def test_nonmaster_writes_only_epoch_key(self, tmp_path,
                                             monkeypatch):
        from imaginaire_tpu.resilience import runstate

        monkeypatch.setattr(
            "imaginaire_tpu.parallel.mesh.get_rank", lambda: 1)
        cluster.set_membership_epoch(2)
        try:
            ck = str(tmp_path / "ck")
            runstate.write_runstate(ck, runstate.build_runstate(0, 4, 1))
        finally:
            cluster.set_membership_epoch(None)
        assert os.path.exists(ck + ".runstate.e2.p1.json")
        assert not os.path.exists(ck + ".runstate.json")
        assert not os.path.exists(ck + ".runstate.p1.json")

    def test_remap_falls_back_to_legacy_master(self, tmp_path, caplog):
        import logging as _logging

        from imaginaire_tpu.resilience import runstate

        ck = str(tmp_path / "ck")
        # checkpoint written by the PRE-resize membership (epoch 0)
        with open(ck + ".runstate.json", "w") as f:
            json.dump(runstate.build_runstate(1, 6, 2), f)
        cluster.set_membership_epoch(1)
        try:
            with caplog.at_level(_logging.INFO,
                                 logger="imaginaire_tpu.resilience"
                                        ".runstate"):
                got = runstate.read_runstate(ck, process_index=1)
        finally:
            cluster.set_membership_epoch(None)
        assert got is not None and got["iteration"] == 6
        assert any("runstate remap" in r.message for r in caplog.records)

    def test_own_epoch_sidecar_wins_no_remap(self, tmp_path, caplog):
        import logging as _logging

        from imaginaire_tpu.resilience import runstate

        ck = str(tmp_path / "ck")
        with open(ck + ".runstate.json", "w") as f:
            json.dump(runstate.build_runstate(0, 2, 0), f)
        with open(ck + ".runstate.e1.p1.json", "w") as f:
            json.dump(runstate.build_runstate(1, 9, 4), f)
        cluster.set_membership_epoch(1)
        try:
            with caplog.at_level(_logging.INFO,
                                 logger="imaginaire_tpu.resilience"
                                        ".runstate"):
                got = runstate.read_runstate(ck, process_index=1)
        finally:
            cluster.set_membership_epoch(None)
        assert got["iteration"] == 9 and got["batch_in_epoch"] == 4
        assert not any("runstate remap" in r.message
                       for r in caplog.records)

    def test_integrity_knows_epoch_sidecars(self, tmp_path):
        from imaginaire_tpu.resilience import integrity

        assert integrity.runstate_index("ck.runstate.e2.p3.json") == 3
        assert integrity.runstate_index("ck.runstate.p3.json") == 3
        assert integrity.runstate_index("ck.runstate.json") is None
        assert integrity.runstate_epoch("ck.runstate.e2.p3.json") == 2
        assert integrity.runstate_epoch("ck.runstate.p3.json") == 0
        assert integrity.runstate_epoch("ck.runstate.json") == 0
        assert integrity.runstate_epoch("ck.partition.json") is None
        # epoch-keyed sidecars from a larger world are orphans too
        ck = str(tmp_path / "ck")
        for name in (".runstate.json", ".runstate.e1.p1.json",
                     ".runstate.e1.p4.json"):
            with open(ck + name, "w") as f:
                f.write("{}")
        orphans = integrity.orphan_sidecars(ck, world_size=2)
        assert [os.path.basename(p) for p in orphans] == \
            ["ck.runstate.e1.p4.json"]


# ------------------------------------------------------ harness verdict


class TestHarnessExitMap:
    def _mod(self):
        import importlib.util

        path = os.path.join(os.path.dirname(__file__), "..",
                            "scripts", "launch_local_pod.py")
        spec = importlib.util.spec_from_file_location(
            "launch_local_pod", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_parse_exit_map(self):
        mod = self._mod()
        assert mod.parse_exit_map("0:75,1:0,2:0") == {0: 75, 1: 0, 2: 0}
        assert mod.parse_exit_map(None) == {}
        assert mod.parse_exit_map("") == {}
        with pytest.raises(ValueError):
            mod.parse_exit_map("nonsense")

    def test_expect_exit_map_flag_parses(self):
        mod = self._mod()
        args = mod.parse_args(["--num-processes", "2",
                               "--expect-exit-map", "0:75,1:0",
                               "--", "train.py"])
        assert args.expect_exit_map == {0: 75, 1: 0}

    def test_elastic_defaults_child_log_dir(self, tmp_path):
        mod = self._mod()
        args = mod.parse_args(["--elastic", "--logdir", str(tmp_path),
                               "--relaunch", "--", "train.py"])
        assert args.relaunch
        assert args.child_log_dir == os.path.join(str(tmp_path),
                                                  "pod-logs")

"""LMDB backend coverage (ref: imaginaire/datasets/lmdb.py:17-79,
utils/lmdb.py:56-129).

The CI image does not ship the ``lmdb`` package, so the round-trip test
skips VISIBLY (it runs anywhere lmdb is installed); the always-run tests
pin the loud import-gate errors so the backend can never silently
pretend to work without its dependency. README flags the backend as
untested in this image.
"""

import numpy as np
import pytest

from imaginaire_tpu.data.backends import LMDBBackend, build_lmdb_dataset


class TestImportGate:
    def test_reader_raises_loudly_without_lmdb(self, tmp_path):
        try:
            import lmdb  # noqa: F401
            pytest.skip("lmdb installed; gate path not reachable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="lmdb.*not installed"):
            LMDBBackend(str(tmp_path))

    def test_writer_raises_loudly_without_lmdb(self, tmp_path):
        try:
            import lmdb  # noqa: F401
            pytest.skip("lmdb installed; gate path not reachable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="lmdb.*not installed"):
            build_lmdb_dataset(str(tmp_path), str(tmp_path / "out"),
                               ["images"])


class TestRoundTrip:
    def test_build_then_read(self, tmp_path):
        """Writer -> reader round trip through the real lmdb package
        (runs only where lmdb is installed; skips visibly here).
        Layout: data_root/<type>/<sequence>/<stem>.<ext>, LMDB key
        '<sequence>/<stem>' (ref: utils/lmdb.py:56-129)."""
        pytest.importorskip(
            "lmdb",
            reason="INTENTIONAL skip: the lmdb package is absent from "
                   "this image (no egress). The import-gate tests above "
                   "still pin the loud-failure contract; packed-shard is "
                   "the tested primary format (see README). This test "
                   "runs wherever lmdb is installed.")
        import cv2

        root = tmp_path / "raw"
        (root / "images" / "seq0").mkdir(parents=True)
        rng = np.random.RandomState(0)
        for name in ("a", "b"):
            cv2.imwrite(str(root / "images" / "seq0" / f"{name}.png"),
                        rng.randint(0, 255, (16, 16, 3), np.uint8))
        out = tmp_path / "lmdb"
        build_lmdb_dataset(str(root), str(out), ["images"])

        backend = LMDBBackend(str(out / "images"))
        img = backend.getitem("seq0/a")
        assert img.shape[:2] == (16, 16)
        with pytest.raises(KeyError):
            backend.getitem("seq0/missing")

"""XLA observability coverage (ISSUE 5): compile-ledger schema +
jsonl round-trip, recompile-tripwire semantics (fires on dtype/shape
drift naming the changed leaf, silent on warm re-calls / shape-growth
labels / allowlisted re-jits, raises under strict), CPU graceful
degradation of the HBM paths, OOM forensics from a faked
RESOURCE_EXHAUSTED, and the report + check_run_health gate legs for
``xla/recompiles`` and the memory-budget watermark."""

import json
import logging
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu import telemetry
from imaginaire_tpu.telemetry import core as tcore
from imaginaire_tpu.telemetry import xla_obs
from imaginaire_tpu.telemetry.report import render_report, summarize

HERE = os.path.dirname(__file__)
ROOT = os.path.abspath(os.path.join(HERE, ".."))
sys.path.insert(0, ROOT)

from scripts.check_run_health import check_health  # noqa: E402


@pytest.fixture
def obs_sandbox():
    """Isolate BOTH process singletons: a fresh ledger + settings and
    a restorable telemetry instance per test."""
    old_tm = tcore._TELEMETRY
    xla_obs._reset_for_tests()
    yield
    tcore._TELEMETRY.shutdown()
    tcore._TELEMETRY = old_tm
    xla_obs._reset_for_tests()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------------- the ledger


def test_ledger_records_compile_with_memory_and_flops(obs_sandbox):
    prog = xla_obs.compiled_program("toy", lambda x: x @ x.T)
    out = prog(jnp.ones((4, 8)))
    assert out.shape == (4, 4)
    led = xla_obs.ledger()
    assert len(led.records) == 1
    entry = led.records[0]
    assert entry["label"] == "toy"
    assert entry["lower_ms"] >= 0 and entry["compile_ms"] > 0
    assert entry["recompile"] is False
    # memory_analysis is real on CPU for arguments/outputs
    assert entry["memory"]["argument_bytes"] > 0
    assert entry["memory"]["output_bytes"] > 0
    assert entry["flops"] and entry["flops"] > 0
    assert led.label_flops["toy"] == entry["flops"]


def test_warm_recall_is_a_cache_hit_not_a_compile(obs_sandbox):
    prog = xla_obs.compiled_program("toy", lambda x: x * 2)
    x = jnp.ones((3, 3))
    a, b, c = prog(x), prog(x), prog(x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(c))
    led = xla_obs.ledger()
    assert len(led.records) == 1
    assert led.cache_hits["toy"] == 2
    assert led.recompiles == 0
    assert prog._cache_size() == 1


def test_ledger_jsonl_roundtrip(obs_sandbox, tmp_path):
    """Every compile lands in compile_ledger.jsonl with the schema the
    forensics tooling parses — including compiles that predate
    telemetry.configure (replayed when the logdir arrives)."""
    prog = xla_obs.compiled_program("pre", lambda x: x + 1)
    prog(jnp.ones((2,)))  # before configure: buffered in memory
    tm = telemetry.configure(logdir=str(tmp_path), enabled=True,
                             sinks=["jsonl"], flush_every_n_steps=0)
    post = xla_obs.compiled_program("post", lambda x: x - 1)
    post(jnp.ones((2,)))
    tm.shutdown()

    entries = _read_jsonl(str(tmp_path / "compile_ledger.jsonl"))
    by_label = {e["label"]: e for e in entries}
    assert set(by_label) == {"pre", "post"}
    for entry in entries:
        assert entry["kind"] == "compile"
        assert {"label", "t", "fingerprint", "lower_ms", "compile_ms",
                "recompile", "expected", "counted_recompile", "memory",
                "flops"} <= set(entry)
        assert len(entry["fingerprint"]) == 12
    # the replayed pre-configure compile also reached the telemetry
    # jsonl as xla/compile/* counters
    events = _read_jsonl(str(tmp_path / "telemetry.jsonl"))
    counters = {e["name"] for e in events if e["kind"] == "counter"}
    assert "xla/compile/pre/count" in counters
    assert "xla/compile/pre/argument_bytes" in counters
    assert "xla/compile/post/count" in counters


# ----------------------------------------------------------- the tripwire


def test_tripwire_names_changed_leaf_on_dtype_change(obs_sandbox,
                                                     caplog):
    prog = xla_obs.compiled_program("step", lambda d: d["x"] * 2)
    prog({"x": jnp.ones((4, 4), jnp.float32)})
    with caplog.at_level(logging.WARNING,
                         logger="imaginaire_tpu.telemetry.xla_obs"):
        prog({"x": jnp.ones((4, 4), jnp.bfloat16)})
    led = xla_obs.ledger()
    assert led.recompiles == 1
    entry = led.records[-1]
    assert entry["counted_recompile"] is True
    (path, (old, new)), = entry["diff"]["changed"].items()
    assert "'x'" in path or "x" in path
    assert "float32" in old and "bfloat16" in new
    assert entry["diff"]["shape_only"] is False
    # the warning names the leaf too
    assert any("RECOMPILE of step" in r.message and "bfloat16" in r.message
               for r in caplog.records)


def test_tripwire_counts_shape_change_unless_label_allows_growth(
        obs_sandbox):
    strict_prog = xla_obs.compiled_program("fixed", lambda x: x * 2)
    strict_prog(jnp.ones((4, 4)))
    strict_prog(jnp.ones((8, 4)))
    assert xla_obs.ledger().recompiles == 1
    assert xla_obs.ledger().records[-1]["diff"]["shape_only"] is True

    poly = xla_obs.compiled_program("poly", lambda x: x * 2,
                                    allow_shape_growth=True)
    poly(jnp.ones((4, 4)))
    poly(jnp.ones((8, 4)))
    led = xla_obs.ledger()
    assert led.recompiles == 1  # unchanged: poly's growth is expected
    assert led.records[-1]["expected"] == "shape_growth"
    # but a dtype flip on a shape-poly label still counts
    poly(jnp.ones((8, 4), jnp.bfloat16))
    assert led.recompiles == 2


def test_sharding_settle_after_first_step_is_expected(obs_sandbox):
    """The train.py warmup transition: uncommitted init state comes
    back from step 1 as committed NamedSharding arrays — the resulting
    re-specialization is expected (plain jit recompiles there too),
    but the REVERSE transition still counts."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    prog = xla_obs.compiled_program("gen_step", lambda s: s["p"] * 2)
    uncommitted = {"p": jnp.ones((4, 4))}
    committed = jax.device_put(uncommitted, NamedSharding(mesh, P()))
    prog(uncommitted)
    prog(committed)
    led = xla_obs.ledger()
    assert led.recompiles == 0
    assert led.records[-1]["expected"] == "sharding_commit"
    # flip-flopping BACK to a seen fingerprint is a warm hit, not a
    # compile at all
    prog(uncommitted)
    assert led.cache_hits["gen_step"] == 1 and led.recompiles == 0
    # but a committed-spec CHANGE is real input drift and counts
    prog(jax.device_put(uncommitted, NamedSharding(mesh, P("data"))))
    assert led.recompiles == 1
    assert led.records[-1]["diff"]["sharding_settle_only"] is False


def test_strict_recompile_raises(obs_sandbox):
    xla_obs.settings().strict_recompile = True
    prog = xla_obs.compiled_program("step", lambda x: x * 2)
    prog(jnp.ones((2, 2)))
    with pytest.raises(xla_obs.RecompileError, match="step"):
        prog(jnp.ones((3, 3)))


def test_retrace_is_an_expected_rejit(obs_sandbox):
    """The fs_vid2vid finetune pattern: the closure changed, retrace()
    drops cached executables, and the next compile is ledgered as
    expected — no tripwire, no counter."""
    scale = [2.0]
    prog = xla_obs.compiled_program("vid_gen_step",
                                    lambda x: x * scale[0])
    x = jnp.ones((2, 2))
    np.testing.assert_allclose(np.asarray(prog(x)), 2.0)
    scale[0] = 5.0
    prog.retrace("fs_vid2vid finetune re-jit")
    # the re-jit actually retraces (sees the new closure)...
    np.testing.assert_allclose(np.asarray(prog(x)), 5.0)
    led = xla_obs.ledger()
    assert led.recompiles == 0
    assert led.records[-1]["expected"] == "fs_vid2vid finetune re-jit"
    assert led.records[-1]["recompile"] is True


def test_expected_recompiles_allowlist(obs_sandbox):
    xla_obs.settings().expected_recompiles = ("blessed",)
    prog = xla_obs.compiled_program("blessed", lambda x: x * 2)
    prog(jnp.ones((2, 2)))
    prog(jnp.ones((4, 4), jnp.bfloat16))  # would otherwise count
    led = xla_obs.ledger()
    assert led.recompiles == 0
    assert led.records[-1]["expected"] == "xla_obs.expected_recompiles"


def test_donated_step_program_dispatches_through_ledger(obs_sandbox):
    """The trainer-shaped call: dict state donated, dict batch — the
    AOT table serves the executable and donation still invalidates."""
    def step(state, data):
        return {"p": state["p"] - 0.1 * jnp.mean(data["x"])}

    prog = xla_obs.compiled_program("gen_step", step, donate_argnums=(0,))
    state = {"p": jnp.ones((4,))}
    data = {"x": jnp.ones((2, 2))}
    for _ in range(3):
        state = prog(state, data)
    assert prog._cache_size() == 1
    assert xla_obs.ledger().cache_hits["gen_step"] == 2
    np.testing.assert_allclose(np.asarray(state["p"]), 0.7, rtol=1e-6)


# ------------------------------------------------- CPU graceful degradation


def test_memory_paths_degrade_on_cpu(obs_sandbox):
    """CPU memory_stats() is None: the watermark sampler is a no-op,
    peak HBM is None, and the budget report still sizes the state."""
    assert jax.devices()[0].memory_stats() is None  # test premise
    assert xla_obs.device_memory_stats() == {}
    assert xla_obs.peak_hbm_bytes() is None
    sink_events = []

    class _Cap:
        def counter(self, name, value, step=None):
            sink_events.append(name)

    assert xla_obs.sample_memory(tm=_Cap()) == {}
    assert sink_events == []  # no mem/* counters fabricated
    state = {"vars_G": {"params": {"w": jnp.ones((8, 8))}},
             "opt_G": {"m": jnp.ones((8, 8))}}
    report = xla_obs.static_budget_report(state)
    assert report["state_bytes"]["vars_G"] == 8 * 8 * 4
    assert report["state_bytes"]["_total"] == 2 * 8 * 8 * 4
    assert "budget_frac" not in report  # no bytes_limit on CPU
    census = xla_obs.live_array_census()
    assert isinstance(census, list)
    for row in census:
        assert {"dtype", "shape", "count", "total_bytes"} <= set(row)


# --------------------------------------------------------------- forensics


def test_oom_forensics_writes_report_and_reraises(obs_sandbox, tmp_path):
    telemetry.configure(logdir=str(tmp_path), enabled=True,
                        sinks=["jsonl"], flush_every_n_steps=0)
    prog = xla_obs.compiled_program("gen_step", lambda x: x * 2)
    prog(jnp.ones((2, 2)))  # give the report an executable footprint
    err = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying "
                       "to allocate 123456 bytes.")
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        with xla_obs.oom_forensics(context="program:gen_step"):
            raise err
    report = json.load(open(str(tmp_path / "oom_report.json")))
    assert report["context"] == "program:gen_step"
    assert report["requested_bytes"] == 123456
    assert "gen_step" in report["executables"]
    assert isinstance(report["live_array_census"], list)
    assert isinstance(report["watermark_history"], list)
    # non-OOM exceptions pass through without a report
    os.remove(str(tmp_path / "oom_report.json"))
    with pytest.raises(ValueError):
        with xla_obs.oom_forensics(context="x"):
            raise ValueError("shape mismatch")
    assert not os.path.exists(str(tmp_path / "oom_report.json"))


def test_parse_requested_bytes_units():
    assert xla_obs.parse_requested_bytes(
        "Attempting to allocate 1.50GiB in HBM") == int(1.5 * 2**30)
    assert xla_obs.parse_requested_bytes(
        "while allocating 4096 bytes") == 4096
    assert xla_obs.parse_requested_bytes("no numbers here") is None


# ------------------------------------------------- report + health gate


def _jsonl_events(*events):
    return list(events)


def test_report_and_gate_fail_on_recompiles(obs_sandbox):
    events = _jsonl_events(
        {"kind": "counter", "name": "xla/compile/gen_step/count",
         "value": 2, "step": 5, "t": 1.0},
        {"kind": "counter", "name": "xla/recompiles", "value": 1,
         "step": 5, "t": 1.0},
        {"kind": "meta", "name": "xla_recompile", "label": "gen_step",
         "t": 1.0,
         "diff": {"changed": {"[0]['x']": ["f32[4]", "bf16[4]"]},
                  "added": {}, "removed": {}, "shape_only": False}},
    )
    s = summarize(events)
    assert s["xla"]["recompiles"] == 1
    assert s["xla"]["compiles"]["gen_step"] == 2
    failures = check_health(s, max_recompiles=0)
    assert any("recompile" in f for f in failures)
    assert not check_health(s, max_recompiles=1)
    text = render_report(events)
    assert "post-warmup recompile" in text
    assert "gen_step" in text


def test_gate_passes_clean_run_and_mem_budget_breach_fails(obs_sandbox):
    clean = summarize(_jsonl_events(
        {"kind": "counter", "name": "xla/compile/gen_step/count",
         "value": 1, "step": 1, "t": 1.0},
        {"kind": "counter", "name": "xla/recompiles", "value": 0,
         "step": 1, "t": 1.0},
    ))
    assert check_health(clean, max_recompiles=0) == []
    hot = summarize(_jsonl_events(
        {"kind": "counter", "name": "mem/tpu0/peak_bytes_in_use",
         "value": 15e9, "step": 1, "t": 1.0},
        {"kind": "counter", "name": "mem/tpu0/bytes_limit",
         "value": 16e9, "step": 1, "t": 1.0},
    ))
    assert hot["xla"]["mem_peak_frac"] == pytest.approx(15 / 16)
    assert check_health(hot, mem_budget_frac=0.9)
    assert not check_health(hot, mem_budget_frac=0.95)
    # runs with no xla/mem counters at all pass both gates unchanged
    legacy = summarize(_jsonl_events(
        {"kind": "counter", "name": "perf/mfu", "value": 0.4,
         "step": 1, "t": 1.0}))
    assert check_health(legacy, max_recompiles=0,
                        mem_budget_frac=0.9) == []


def test_gate_max_temp_frac_reads_mem_budget_meta(obs_sandbox):
    """--max-temp-frac (ISSUE 10): the worst executable's temp
    allocation as a fraction of bytes_limit, from the ledger's
    mem_budget meta — the static gate on remat/precision regressions."""
    s = summarize(_jsonl_events(
        {"kind": "meta", "name": "mem_budget", "t": 1.0,
         "bytes_limit": 16e9,
         "executables": {
             "gen_step": {"temp_bytes": 12e9, "total_bytes": 13e9},
             "dis_step": {"temp_bytes": 4e9, "total_bytes": 5e9}}}))
    fails = check_health(s, max_temp_frac=0.5)
    assert any("gen_step" in f and "temp" in f for f in fails), fails
    assert check_health(s, max_temp_frac=0.8) == []
    # no bytes_limit recorded (CPU run, observability off) -> no-op
    s2 = summarize(_jsonl_events(
        {"kind": "meta", "name": "mem_budget", "t": 1.0,
         "executables": {"gen_step": {"temp_bytes": 12e9}}}))
    assert check_health(s2, max_temp_frac=0.1) == []


def test_check_run_health_cli_max_recompiles(obs_sandbox, tmp_path):
    """CLI legs: --max-recompiles 0 passes a clean jsonl and fails an
    injected-recompile jsonl (the dryrun acceptance pair)."""
    import subprocess

    clean = tmp_path / "clean.jsonl"
    clean.write_text(json.dumps(
        {"kind": "counter", "name": "xla/recompiles", "value": 0,
         "step": 1, "t": 1.0}) + "\n")
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(
        {"kind": "counter", "name": "xla/recompiles", "value": 3,
         "step": 1, "t": 1.0}) + "\n")
    script = os.path.join(ROOT, "scripts", "check_run_health.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run([sys.executable, script, str(clean),
                         "--max-recompiles", "0"],
                        capture_output=True, text=True, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    fail = subprocess.run([sys.executable, script, str(bad),
                           "--max-recompiles", "0"],
                          capture_output=True, text=True, env=env)
    assert fail.returncode == 1
    assert "recompile" in fail.stdout


# --------------------------------------------- watchdog names the compile


def test_watchdog_dump_names_active_compile(obs_sandbox):
    led = xla_obs.ledger()
    assert xla_obs.active_compile_label() is None
    led.begin("vid_gen_step")
    try:
        assert xla_obs.active_compile_label() == "vid_gen_step"
    finally:
        led.end("vid_gen_step")
    assert xla_obs.active_compile_label() is None


def test_hang_dump_header_includes_compile_label(obs_sandbox, capsys):
    tm = telemetry.configure(enabled=True, sinks=[],
                             flush_every_n_steps=0, hang_timeout_s=0.05)
    led = xla_obs.ledger()
    led.begin("flow_teacher")
    try:
        import time

        deadline = time.time() + 5.0
        while time.time() < deadline:
            if "compiling flow_teacher" in capsys.readouterr().err:
                break
            time.sleep(0.05)
        else:
            pytest.fail("watchdog dump never named the open compile")
    finally:
        led.end("flow_teacher")
        tm.shutdown()

"""Teacher flow cache (flow/cache.py, ISSUE 4): off-step FlowNet2
execution, content-addressed on-disk caching at canonical resolution,
equivariant crop/hflip transforms, step programs free of the teacher
param tree, and the precompute CLI + health-gate satellites."""

import io
import json
import contextlib
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from imaginaire_tpu.config import Config
from imaginaire_tpu.flow.cache import (
    FlowCacheStore,
    TeacherFlowCache,
    content_key,
    flow_cache_settings,
    pair_key,
    transform_flow,
)
from imaginaire_tpu.registry import resolve

CFG = os.path.join(os.path.dirname(__file__), "..", "configs", "unit_test",
                   "vid2vid_street.yaml")


def video_batch(rng, t=3, h=64, w=64, labels=12):
    return {
        "images": np.asarray(rng.rand(1, t, h, w, 3),
                             np.float32) * 2 - 1,
        "label": (rng.rand(1, t, h, w, labels) > 0.9).astype(np.float32),
    }


def make_cfg(tmp_path, cache=None, shrink_perceptual=True):
    cfg = Config(CFG)
    cfg.logdir = str(tmp_path)
    cfg.flow_network = {"allow_random_init": True}
    if cache is not None:
        cfg.flow_cache = dict(cache)
    if shrink_perceptual:
        # equivalence, not capacity (the TestRolloutScan convention)
        cfg.trainer.perceptual_loss.layers = ["relu_1_1", "relu_2_1"]
        cfg.trainer.perceptual_loss.weights = [0.5, 1.0]
    return cfg


# --------------------------------------------------------------- store


class TestStoreAndKeys:
    def test_roundtrip_and_stats(self, rng, tmp_path):
        store = FlowCacheStore(str(tmp_path), "float32")
        flow = rng.rand(8, 8, 2).astype(np.float32) * 40 - 20
        conf = (rng.rand(8, 8, 1) > 0.5).astype(np.float32)
        key = pair_key("d", 0, "seq", "b", "a", (8, 8), "t")
        assert store.get(key) is None
        store.put(key, flow, conf)
        flow2, conf2 = store.get(key)
        np.testing.assert_array_equal(flow2, flow)
        np.testing.assert_array_equal(conf2, conf)
        assert store.stats() == {"hits": 1, "misses": 1,
                                 "corrupt_shards": 0, "hit_rate": 0.5}

    def test_float16_storage_tolerance(self, rng, tmp_path):
        store = FlowCacheStore(str(tmp_path), "float16")
        flow = rng.rand(8, 8, 2).astype(np.float32) * 40 - 20
        conf = np.ones((8, 8, 1), np.float32)
        key = pair_key("d", 0, "seq", "b", "a", (8, 8), "t")
        store.put(key, flow, conf)
        flow2, _ = store.get(key)
        # |flow| <= 40 px -> float16 quantization < 0.05 px
        np.testing.assert_allclose(flow2, flow, atol=0.05)

    def test_key_invalidation(self):
        base = pair_key("d", 0, "seq", "f1", "f0", (64, 64), "t1")
        # resolution change invalidates
        assert base != pair_key("d", 0, "seq", "f1", "f0", (128, 64), "t1")
        # teacher-weights change invalidates
        assert base != pair_key("d", 0, "seq", "f1", "f0", (64, 64), "t2")
        # different frame pair / sequence / root
        assert base != pair_key("d", 0, "seq", "f2", "f1", (64, 64), "t1")
        assert base != pair_key("d", 1, "seq", "f1", "f0", (64, 64), "t1")
        # the key is CANONICAL: crop/flip draws do not enter it — that is
        # the whole point of the equivariant transform
        assert base == pair_key("d", 0, "seq", "f1", "f0", (64, 64), "t1")

    def test_content_key_tracks_bytes(self, rng):
        a = rng.rand(1, 3, 8, 8, 3).astype(np.float32)
        b = a.copy()
        b[0, 0, 0, 0, 0] += 1e-3
        assert content_key(a, "t") == content_key(a.copy(), "t")
        assert content_key(a, "t") != content_key(b, "t")
        assert content_key(a, "t") != content_key(a, "t2")

    def test_corrupt_shard_degrades_to_miss(self, rng, tmp_path):
        store = FlowCacheStore(str(tmp_path), "float32")
        key = pair_key("d", 0, "seq", "b", "a", (8, 8), "t")
        path = store.path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(b"not an npz")
        assert store.get(key) is None


# ----------------------------------------------------------- transform


class TestTransform:
    def test_hflip_oracle(self, rng):
        flow = rng.rand(2, 6, 8, 2).astype(np.float32) * 10 - 5
        conf = rng.rand(2, 6, 8, 1).astype(np.float32)
        tf, tc = transform_flow(flow, conf, {"hflip": True, "crop": None})
        h, w = 6, 8
        for y in range(h):
            for x in range(w):
                np.testing.assert_allclose(
                    tf[:, y, x, 0], -flow[:, y, w - 1 - x, 0])
                np.testing.assert_allclose(
                    tf[:, y, x, 1], flow[:, y, w - 1 - x, 1])
                np.testing.assert_allclose(
                    tc[:, y, x, 0], conf[:, y, w - 1 - x, 0])

    def test_crop_is_pure_slice(self, rng):
        flow = rng.rand(2, 6, 8, 2).astype(np.float32)
        conf = rng.rand(2, 6, 8, 1).astype(np.float32)
        tf, tc = transform_flow(flow, conf,
                                {"crop": (1, 2, 4, 5), "hflip": False})
        np.testing.assert_array_equal(tf, flow[:, 1:5, 2:7])
        np.testing.assert_array_equal(tc, conf[:, 1:5, 2:7])

    def test_crop_then_flip_order(self, rng):
        flow = rng.rand(1, 6, 8, 2).astype(np.float32)
        conf = rng.rand(1, 6, 8, 1).astype(np.float32)
        tf, _ = transform_flow(flow, conf,
                               {"crop": (0, 1, 4, 5), "hflip": True})
        manual = flow[:, 0:4, 1:6][:, :, ::-1] * np.asarray([-1.0, 1.0])
        np.testing.assert_allclose(tf, manual)


# ------------------------------------------- equivariance (toy teacher)


def toy_flow(im_a, im_b, radius=2):
    """Brute-force integer block matcher: per-pixel shift minimizing the
    3x3-summed SSD (wrap borders). A real — if crude — flow estimator
    that is exactly flip- and (interior-)crop-equivariant, so the cache
    transform can be pinned without CNN non-equivariance noise."""
    cost_best = np.full(im_a.shape[:2], np.inf)
    flow = np.zeros(im_a.shape[:2] + (2,), np.float32)
    for dv in range(-radius, radius + 1):
        for du in range(-radius, radius + 1):
            # flow convention: value (du, dv) at x means the match in
            # im_b sits at x - (du, dv)
            shifted = np.roll(im_b, (dv, du), axis=(0, 1))
            d = ((im_a.astype(np.float64) - shifted) ** 2).sum(-1)
            s = sum(np.roll(d, (i, j), axis=(0, 1))
                    for i in (-1, 0, 1) for j in (-1, 0, 1))
            m = s < cost_best
            cost_best = np.where(m, s, cost_best)
            flow[m] = (du, dv)
    return flow, np.ones(im_a.shape[:2] + (1,), np.float32)


class ToyWrapper:
    """Duck-typed FlowNet stand-in for TeacherFlowCache."""

    params = None
    weights_path = None

    def _jit_flow(self, params, im_a, im_b):
        flows = np.stack([toy_flow(a, b)[0] for a, b in zip(im_a, im_b)])
        confs = np.ones(flows.shape[:-1] + (1,), np.float32)
        return flows, confs


class TestEquivariance:
    """Cached-and-transformed (flow, conf) vs the teacher run directly
    on the augmented frames: exact for hflip, boundary-band tolerance
    for crop (the matcher wraps at borders, real flow estimators lose
    context there the same way)."""

    RADIUS = 2
    BAND = RADIUS + 2  # search radius + box window

    def _pair(self, rng, h=24, w=32, shift=(2, -1)):
        a = rng.rand(h, w, 3).astype(np.float32)
        b = np.roll(a, (shift[1], shift[0]), axis=(0, 1))  # (dv, du)
        return a, b

    def _run_cache(self, metas, images, tmp_path):
        cache = TeacherFlowCache(
            ToyWrapper(),
            flow_cache_settings({"flow_cache": {
                "enabled": True, "mode": "disk",
                "store_dtype": "float32"}}),
            cache_dir=str(tmp_path / "store"))
        batch = cache.attach({"images": images, "_flow_cache": metas})
        return cache, batch["flow_gt"], batch["conf_gt"]

    def test_hflip_exact(self, rng, tmp_path):
        a, b = self._pair(rng)
        h, w = a.shape[:2]
        record = {"canonical_hw": (h, w), "crop": None, "hflip": True,
                  "canonical_ok": True}
        keys = [pair_key("toy", 0, "s", "f1", "f0", (h, w), "toy")]
        # augmented = flipped canonical; teacher pair order is
        # (target=frame1, prev=frame0) -> src order [b(prev), a... ]:
        # frames are [f0, f1] = [b_prev, a_tgt]? use [a0, a1] = (b, a)
        src = np.stack([b, a])  # frames f0, f1
        aug = src[:, :, ::-1]  # hflip
        images = aug[None]  # (1, 2, h, w, 3)
        _, flow_gt, conf_gt = self._run_cache(
            [{"record": record, "keys": keys, "src": src}], images,
            tmp_path)
        direct, _ = toy_flow(aug[1], aug[0], self.RADIUS)
        np.testing.assert_array_equal(flow_gt[0, 0], direct)

    def test_crop_interior_exact(self, rng, tmp_path):
        a, b = self._pair(rng)
        h, w = a.shape[:2]
        top, left, ch, cw = 3, 5, 16, 20
        record = {"canonical_hw": (h, w),
                  "crop": (top, left, ch, cw), "hflip": False,
                  "canonical_ok": True}
        keys = [pair_key("toy", 0, "s", "f1", "f0", (h, w), "toy")]
        src = np.stack([b, a])
        aug = src[:, top:top + ch, left:left + cw]
        images = aug[None]
        _, flow_gt, _ = self._run_cache(
            [{"record": record, "keys": keys, "src": src}], images,
            tmp_path)
        direct, _ = toy_flow(aug[1], aug[0], self.RADIUS)
        band = self.BAND
        np.testing.assert_array_equal(
            flow_gt[0, 0, band:-band, band:-band],
            direct[band:-band, band:-band])

    def test_store_hit_path_matches_fresh_compute(self, rng, tmp_path):
        """Second epoch: the dataset loads the canonical shards and the
        producer only transforms — identical supervision, hit_rate 1."""
        a, b = self._pair(rng)
        h, w = a.shape[:2]
        record = {"canonical_hw": (h, w), "crop": (1, 2, 16, 20),
                  "hflip": True, "canonical_ok": True}
        keys = [pair_key("toy", 0, "s", "f1", "f0", (h, w), "toy")]
        src = np.stack([b, a])
        aug = src[:, 1:17, 2:22][:, :, ::-1]
        images = aug[None]
        cache, flow_1, conf_1 = self._run_cache(
            [{"record": record, "keys": keys, "src": src}], images,
            tmp_path)
        assert cache.hit_rate() == 0.0  # cold epoch: all misses
        # warm epoch: the dataset-side hook would load the shards
        cached = [cache.store.get(k) for k in keys]
        assert all(c is not None for c in cached)
        payload = {"record": record, "keys": keys,
                   "flow": np.stack([c[0] for c in cached]),
                   "conf": np.stack([c[1] for c in cached])}
        batch = cache.attach({"images": images, "_flow_cache": [payload]})
        np.testing.assert_array_equal(batch["flow_gt"], flow_1)
        np.testing.assert_array_equal(batch["conf_gt"], conf_1)
        assert cache.hit_rate() == 0.5  # 1 miss epoch + 1 hit epoch


# -------------------------------------------- real teacher, content path


class TestAttachContentPath:
    def test_matches_in_graph_teacher_and_hits_disk(self, rng, tmp_path):
        from imaginaire_tpu.flow import FlowNet

        wrapper = FlowNet(allow_random_init=True)
        wrapper.init_params(jax.random.PRNGKey(0))
        cache = TeacherFlowCache(
            wrapper,
            flow_cache_settings({"flow_cache": {
                "enabled": True, "mode": "disk",
                "store_dtype": "float32"}}),
            cache_dir=str(tmp_path / "store"))
        data = video_batch(rng)
        batch = cache.attach(dict(data))
        assert batch["flow_gt"].shape == (1, 2, 64, 64, 2)
        assert batch["conf_gt"].shape == (1, 2, 64, 64, 1)
        # byte-tolerance equivalence vs the in-graph teacher: the same
        # jitted function on the same (target, prev) pair ordering
        images = data["images"]
        im_a = images[:, 1:].reshape((-1, 64, 64, 3))
        im_b = images[:, :-1].reshape((-1, 64, 64, 3))
        f, c = wrapper._jit_flow(wrapper.params, jnp.asarray(im_a),
                                 jnp.asarray(im_b))
        np.testing.assert_array_equal(
            batch["flow_gt"].reshape(-1, 64, 64, 2), np.asarray(f))
        np.testing.assert_array_equal(
            batch["conf_gt"].reshape(-1, 64, 64, 1), np.asarray(c))
        assert cache.hit_rate() == 0.0
        # identical bytes -> whole-batch disk hit, exact at float32
        batch2 = cache.attach(dict(data))
        np.testing.assert_array_equal(batch2["flow_gt"], batch["flow_gt"])
        assert cache.hit_rate() == 0.5

    def test_non_video_batches_pass_through(self, rng):
        cache = TeacherFlowCache(ToyWrapper(),
                                 flow_cache_settings(
                                     {"flow_cache": {"enabled": True,
                                                     "mode": "producer"}}))
        image_batch = {"images": rng.rand(2, 8, 8, 3).astype(np.float32)}
        out = cache.attach(dict(image_batch))
        assert "flow_gt" not in out
        single = {"images": rng.rand(1, 1, 8, 8, 3).astype(np.float32),
                  "_flow_cache": [{}]}
        out = cache.attach(dict(single))
        assert "flow_gt" not in out and "_flow_cache" not in out


# --------------------------------------------------- trainer integration


class TestTrainerParamTree:
    def test_step_param_tree_loses_flownet(self, tmp_path):
        """The acceptance assertion: with flow_cache.enabled the step
        programs' input tree (state['loss_params']) carries no FlowNet2
        parameters; the in-graph fallback still does."""
        cfg = make_cfg(tmp_path, cache={"enabled": True,
                                        "mode": "producer"})
        cfg.trainer.perceptual_loss = None  # keep this test cheap
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        assert trainer.flow_cache is not None
        params = trainer.init_loss_params(jax.random.PRNGKey(0))
        assert "flownet" not in params

        cfg2 = make_cfg(tmp_path, cache={"enabled": False})
        cfg2.trainer.perceptual_loss = None
        trainer2 = resolve(cfg2.trainer.type, "Trainer")(cfg2)
        assert trainer2.flow_cache is None
        params2 = trainer2.init_loss_params(jax.random.PRNGKey(0))
        assert "flownet" in params2

    def test_disabled_cache_pops_stray_payloads(self, rng, tmp_path):
        cfg = make_cfg(tmp_path, cache={"enabled": False})
        cfg.trainer.perceptual_loss = None
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = dict(video_batch(rng), _flow_cache=[{"record": {}}])
        out = trainer._start_of_iteration(data, 1)
        assert "_flow_cache" not in out


@pytest.mark.slow
class TestCachedRollout:
    def _run(self, tmp_path, cache):
        cfg = make_cfg(tmp_path / ("cache" if cache else "graph"),
                       cache={"enabled": cache, "mode": "disk",
                              "dir": str(tmp_path / "store"),
                              "store_dtype": "float32"})
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = video_batch(np.random.RandomState(7))
        batch = trainer.start_of_iteration(dict(data), 1)
        trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = trainer.gen_update(batch)
        leaf = jax.tree_util.tree_leaves(
            trainer.state["vars_G"]["params"])[0]
        return (trainer,
                {k: float(jax.device_get(v)) for k, v in losses.items()},
                np.asarray(jax.device_get(leaf)))

    def test_cached_rollout_matches_in_graph(self, tmp_path):
        """Full-step equivalence: amortized teacher vs in-graph teacher,
        same data + same seeds -> same losses and same updated params."""
        t_graph, losses_g, leaf_g = self._run(tmp_path, False)
        t_cache, losses_c, leaf_c = self._run(tmp_path, True)
        assert "flownet" in t_graph.state["loss_params"]
        assert "flownet" not in t_cache.state["loss_params"]
        assert set(losses_g) == set(losses_c)
        for k in losses_g:
            np.testing.assert_allclose(losses_c[k], losses_g[k],
                                       rtol=2e-3, atol=2e-4, err_msg=k)
        np.testing.assert_allclose(leaf_c, leaf_g, rtol=2e-3, atol=2e-4)

    def test_prefetched_batches_carry_flow_gt(self, tmp_path):
        """DevicePrefetcher producer thread runs the teacher: batches
        arrive as PrefetchedBatch with (flow, conf) already attached —
        the step loop never touches the teacher."""
        from imaginaire_tpu.data.device_prefetch import (
            DevicePrefetcher,
            PrefetchedBatch,
        )

        cfg = make_cfg(tmp_path, cache={"enabled": True,
                                        "mode": "producer"})
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        rng = np.random.RandomState(7)
        loader = [video_batch(rng) for _ in range(2)]
        prefetcher = DevicePrefetcher(
            loader,
            host_preprocess=lambda b, i: trainer._start_of_iteration(b, i))
        batches = list(prefetcher)
        assert len(batches) == 2
        for batch in batches:
            assert isinstance(batch, PrefetchedBatch)
            assert batch["flow_gt"].shape == (1, 2, 64, 64, 2)
        # consuming a prefetched batch runs the cached-supervision step
        batch = trainer.start_of_iteration(batches[0], 1)
        trainer.init_state(jax.random.PRNGKey(0), batch)
        losses = trainer.gen_update(batch)
        assert "Flow_L1" in losses
        for k, v in losses.items():
            assert np.isfinite(float(jax.device_get(v))), k


# ------------------------------------------- dataset + precompute + gate


class TestPrecomputeAndDataset:
    def _overlay(self, tmp_path):
        with open(CFG) as f:
            user = yaml.safe_load(f)
        user["flow_network"] = {"allow_random_init": True}
        user["flow_cache"] = {"enabled": True,
                              "dir": str(tmp_path / "store"),
                              "store_dtype": "float32"}
        path = str(tmp_path / "cfg.yaml")
        with open(path, "w") as f:
            yaml.safe_dump(user, f)
        return path

    def _precompute(self, cfg_path):
        from scripts.precompute_flow import main

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(["--config", cfg_path, "--json"])
        return rc, json.loads(buf.getvalue().strip().splitlines()[-1])

    def test_precompute_smoke_second_run_all_hits(self, tmp_path):
        cfg_path = self._overlay(tmp_path)
        rc, s1 = self._precompute(cfg_path)
        assert rc == 0
        assert s1["pairs"] == 2 and s1["misses"] == 2  # 3 fixture frames
        rc, s2 = self._precompute(cfg_path)
        assert rc == 0
        assert s2["hit_rate"] == 1.0 and s2["misses"] == 0

        # the warmed store serves the dataset hook: items carry the
        # canonical (flow, conf), zero teacher cost at train time
        cfg = Config(cfg_path)
        ds = resolve(cfg.data.type, "Dataset")(cfg)
        assert ds._flow_hook is not None and ds._flow_hook.active
        item = ds[0]
        payload = item["_flow_cache"]
        assert payload["flow"] is not None
        assert payload["flow"].shape == (2, 64, 64, 2)
        assert payload["record"]["canonical_hw"] == (64, 64)

    def test_dataset_miss_ships_canonical_src(self, tmp_path):
        cfg = Config(self._overlay(tmp_path))  # store never warmed
        ds = resolve(cfg.data.type, "Dataset")(cfg)
        item = ds[0]
        payload = item["_flow_cache"]
        assert payload.get("flow") is None
        assert payload["src"].shape == (3, 64, 64, 3)
        # teacher-input range: the fixture images are normalize: True
        assert payload["src"].min() >= -1.0 and payload["src"].max() <= 1.0

    def test_inference_items_carry_no_payload(self, tmp_path):
        cfg = Config(self._overlay(tmp_path))
        ds = resolve(cfg.data.type, "Dataset")(cfg, is_inference=True)
        assert ds._flow_hook is None

    def test_health_gate_accepts_flow_cache_counters(self, tmp_path):
        """The CI gate must treat flow_cache/* counters as benign (and
        surface them), with or without --require-health."""
        from scripts.check_run_health import main

        run_dir = tmp_path / "run"
        os.makedirs(run_dir)
        events = [
            {"kind": "counter", "name": "health/G/grad_norm/_total",
             "value": 1.0, "step": 10, "t": 1.0},
            {"kind": "counter", "name": "flow_cache/hit_rate",
             "value": 1.0, "step": 10, "t": 1.0},
            {"kind": "counter", "name": "flow_cache/compute_ms",
             "value": 5.0, "step": 10, "t": 1.0},
        ]
        with open(run_dir / "telemetry.jsonl", "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main([str(run_dir), "--require-health", "--json"])
        assert rc == 0, buf.getvalue()
        verdict = json.loads(buf.getvalue())
        assert verdict["healthy"]
        assert verdict["flow_cache"]["present"]
        assert verdict["flow_cache"]["hit_rate"] == 1.0
        assert verdict["flow_cache"]["compute_ms_mean"] == 5.0

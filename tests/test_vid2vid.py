"""vid2vid: video dataset + curriculum, interleaved rollout training,
flow warp and temporal discriminator activation (mirrors the reference's
2-iter smoke strategy, SURVEY.md §4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu.config import Config
from imaginaire_tpu.registry import resolve

CFG = os.path.join(os.path.dirname(__file__), "..", "configs", "unit_test",
                   "vid2vid_street.yaml")


def video_batch(rng, t=3, h=64, w=64, labels=12):
    return {
        "images": jnp.asarray(
            rng.rand(1, t, h, w, 3).astype(np.float32)) * 2 - 1,
        "label": jnp.asarray(
            (rng.rand(1, t, h, w, labels) > 0.9).astype(np.float32)),
    }


class TestPairedVideoDataset:
    def test_sequence_sampling_and_curriculum(self):
        cfg = Config(CFG)
        ds = resolve(cfg.data.type, "Dataset")(cfg)
        assert ds.sequence_length == 3
        item = ds[0]
        assert item["images"].shape == (3, 64, 64, 3)
        assert item["label"].shape == (3, 64, 64, 12)
        ds.set_sequence_length(1)
        item = ds[0]
        assert item["images"].shape == (1, 64, 64, 3)
        # requesting beyond the max clamps
        ds.set_sequence_length(100)
        assert ds.sequence_length == 3


@pytest.mark.slow
class TestVid2VidTraining:
    def test_rollout_two_iterations(self, rng, tmp_path):
        """3-frame interleaved rollout: frame 0 runs the first-frame
        trunk, frame 2 has num_frames_G-1 prevs so the flow warp and the
        temporal discriminator activate."""
        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        trainer.init_state(jax.random.PRNGKey(0), video_batch(rng))
        trainer.start_of_epoch(0)
        for it in range(1, 3):
            batch = trainer.start_of_iteration(video_batch(rng), it)
            trainer.dis_update(batch)  # no-op by contract
            g = trainer.gen_update(batch)
            trainer.end_of_iteration(batch, 0, it)
        for name, v in g.items():
            assert np.isfinite(float(jax.device_get(v))), name
        # flow loss active (warp happened) and temporal GAN active
        assert "Flow" in g
        assert "GAN_T0" in g
        assert {"GAN", "FeatureMatching", "Perceptual", "total"} <= set(g)

    def test_single_frame_no_temporal(self, rng, tmp_path):
        """A 1-frame sequence uses only the image path: no flow, no
        temporal loss."""
        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        trainer.init_state(jax.random.PRNGKey(0), video_batch(rng, t=1))
        batch = trainer.start_of_iteration(video_batch(rng, t=1), 1)
        g = trainer.gen_update(batch)
        assert "Flow" not in g
        assert "GAN_T0" not in g
        for name, v in g.items():
            assert np.isfinite(float(jax.device_get(v))), name

    def test_generator_paths(self, rng, tmp_path):
        """First-frame vs continuation vs warp paths produce the right
        outputs."""
        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = video_batch(rng)
        trainer.init_state(jax.random.PRNGKey(0), data)
        variables = trainer.state["vars_G"]
        label = data["label"][:, 0]
        # first frame: no flow outputs
        out, _ = trainer._apply_G(variables, {"label": label},
                                  jax.random.PRNGKey(0), False)
        assert out["fake_images"].shape == (1, 64, 64, 3)
        assert out["fake_flow_maps"] is None
        # continuation with full prev stack: flow + warp + mask present
        prevs = {
            "label": data["label"][:, 2],
            "prev_labels": data["label"][:, :2],
            "prev_images": data["images"][:, :2],
        }
        out2, _ = trainer._apply_G(variables, prevs, jax.random.PRNGKey(0),
                                   False)
        assert out2["fake_flow_maps"].shape == (1, 64, 64, 2)
        assert out2["fake_occlusion_masks"].shape == (1, 64, 64, 1)
        assert out2["warped_images"].shape == (1, 64, 64, 3)

    def test_flownet_teacher_wiring(self, rng, tmp_path):
        """cfg.flow_network activates the FlowNet2-teacher FlowLoss path:
        weights registered, teacher params in loss_params, and the
        teacher-driven loss terms compute on real data shapes."""
        import jax.numpy as jnp

        from imaginaire_tpu.losses.flow import FlowLoss

        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        cfg.flow_network = {"allow_random_init": True}
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        assert trainer.flow_net_wrapper is not None
        assert {"Flow_L1", "Flow_Warp", "Flow_Mask"} <= set(trainer.weights)
        # FlowLoss consumes the teacher's (flow, conf) on vid2vid outputs
        a = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32))
        b = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32))
        fl = FlowLoss(trainer.flow_net_wrapper)
        out = {"fake_images": a,
               "warped_images": b,
               "fake_flow_maps": jnp.zeros((1, 64, 64, 2)),
               "fake_occlusion_masks": jnp.full((1, 64, 64, 1), 0.5)}
        l1, warp, mask = fl({"image": a, "real_prev_image": b}, out)
        for v in (l1, warp, mask):
            assert np.isfinite(float(v))

    def test_curriculum_epoch_schedule(self, rng, tmp_path):
        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        cfg.single_frame_epoch = 2
        cfg.num_epochs_temporal_step = 2

        class FakeLoader:
            class dataset:
                sequence_length_max = 3
                seq = None

                @classmethod
                def set_sequence_length(cls, n):
                    cls.seq = n

            def __len__(self):
                return 1

        trainer = resolve(cfg.trainer.type, "Trainer")(
            cfg, train_data_loader=FakeLoader())
        trainer._start_of_epoch(0)
        assert trainer.sequence_length == 1
        trainer._start_of_epoch(2)  # temporal init
        assert trainer.sequence_length == 3  # initial (3) clamped to max
        assert FakeLoader.dataset.seq == 3


class TestDensePosePreprocessing:
    def test_pre_process_densepose(self):
        from imaginaire_tpu.config import AttrDict
        from imaginaire_tpu.model_utils.fs_vid2vid import pre_process_densepose

        rng = np.random.RandomState(0)
        pose = rng.rand(1, 8, 8, 6).astype(np.float32)
        pose[..., 2] = rng.randint(0, 25, (1, 8, 8)) / 255.0  # part ids
        cfg = AttrDict({"random_drop_prob": 0.0})
        out = pre_process_densepose(cfg, pose)
        assert out.min() >= -1.0 and out.max() <= 1.0
        # part channel rescaled 24 -> 255 range before normalization
        np.testing.assert_allclose(
            out[..., 2], (pose[..., 2] * 255 / 24) * 2 - 1, rtol=1e-5)

    def test_random_drop_zeroes_parts(self):
        import random

        from imaginaire_tpu.config import AttrDict
        from imaginaire_tpu.model_utils.fs_vid2vid import pre_process_densepose

        pose = np.ones((1, 4, 4, 3), np.float32) * 0.5
        pose[..., 2] = 5 / 255.0  # every pixel is part 5
        cfg = AttrDict({"random_drop_prob": 1.0})
        out = pre_process_densepose(cfg, pose, rng=random.Random(0))
        # part 5 dropped everywhere -> densepose channels at -1 (zero
        # before renormalization)
        np.testing.assert_allclose(out[..., :3], -1.0)


@pytest.mark.slow
class TestVideoFID:
    def test_video_fid_end_to_end(self, tmp_path):
        """Video FID: pinned-sequence val loader -> reset/test_single
        rollout -> Inception activations -> Frechet distance
        (ref: trainers/vid2vid.py:697-757, evaluation/common.py:79-158)."""
        from imaginaire_tpu.data.loader import DataLoader

        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        cfg.trainer.fid_random_init = True  # no ported weights in tests
        cfg.trainer.num_videos_to_test = 1
        ds_cls = resolve(cfg.data.type, "Dataset")
        val_ds = ds_cls(cfg, is_inference=True)
        assert val_ds.num_inference_sequences() == 1
        val_ds.set_inference_sequence_idx(0)
        assert len(val_ds) == 3  # 3 fixture frames
        item = val_ds[0]
        assert item["images"].shape == (1, 64, 64, 3)
        loader = DataLoader(val_ds, batch_size=1, shuffle=False,
                            drop_last=False)
        trainer = resolve(cfg.trainer.type, "Trainer")(
            cfg, val_data_loader=loader)
        rng = np.random.RandomState(0)
        batch = {
            "images": jnp.asarray(
                rng.rand(1, 3, 64, 64, 3).astype(np.float32)) * 2 - 1,
            "label": jnp.asarray(
                (rng.rand(1, 3, 64, 64, 12) > 0.9).astype(np.float32)),
        }
        trainer.init_state(jax.random.PRNGKey(0), batch)
        fid = trainer._compute_fid()
        assert fid is not None and np.isfinite(fid) and fid > 0
        # cached real stats file written
        import glob
        assert glob.glob(str(tmp_path) + "/real_stats_video_*.npz")

    def test_video_kid_prdc(self, tmp_path):
        """Video-family KID/PRDC: the same pinned-sequence rollout as
        video FID feeds kid/prdc_from_activations
        (ref: evaluation/kid.py:29, prdc.py)."""
        from imaginaire_tpu.data.loader import DataLoader

        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        cfg.trainer.fid_random_init = True
        cfg.trainer.num_videos_to_test = 1
        ds_cls = resolve(cfg.data.type, "Dataset")
        val_ds = ds_cls(cfg, is_inference=True)
        loader = DataLoader(val_ds, batch_size=1, shuffle=False,
                            drop_last=False)
        trainer = resolve(cfg.trainer.type, "Trainer")(
            cfg, val_data_loader=loader)
        rng = np.random.RandomState(0)
        batch = {
            "images": jnp.asarray(
                rng.rand(1, 3, 64, 64, 3).astype(np.float32)) * 2 - 1,
            "label": jnp.asarray(
                (rng.rand(1, 3, 64, 64, 12) > 0.9).astype(np.float32)),
        }
        trainer.init_state(jax.random.PRNGKey(0), batch)
        out = trainer.compute_extra_metrics(["kid", "prdc"])
        assert np.isfinite(out["KID"])
        for k in ("precision", "recall", "density", "coverage"):
            v = out[f"PRDC_{k}"]
            assert np.isfinite(v) and 0.0 <= v, (k, v)
        # unsupported requests return {} (evaluate.py turns that into a
        # hard failure)
        assert trainer.compute_extra_metrics(["nope"]) == {}


@pytest.mark.slow
class TestVideoInference:
    def test_test_writes_all_frames_per_sequence(self, tmp_path):
        """trainer.test over an inference dataset pins each sequence and
        writes every frame (ref: trainers/vid2vid.py:330-417)."""
        from imaginaire_tpu.data.loader import DataLoader

        cfg = Config(CFG)
        cfg.logdir = str(tmp_path)
        ds = resolve(cfg.data.type, "Dataset")(cfg, is_inference=True)
        loader = DataLoader(ds, batch_size=1, shuffle=False,
                            drop_last=False)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        rng = np.random.RandomState(0)
        batch = {
            "images": jnp.asarray(
                rng.rand(1, 3, 64, 64, 3).astype(np.float32)) * 2 - 1,
            "label": jnp.asarray(
                (rng.rand(1, 3, 64, 64, 12) > 0.9).astype(np.float32)),
        }
        trainer.init_state(jax.random.PRNGKey(0), batch)
        out_dir = str(tmp_path / "out")
        trainer.test(loader, out_dir, None)
        import glob
        frames = sorted(glob.glob(out_dir + "/seq0000/*.jpg"))
        assert len(frames) == 3  # all fixture frames, not just frame 0


@pytest.mark.slow
class TestMultiDeviceVid2Vid:
    def test_sharded_interleaved_rollout(self, rng, tmp_path):
        """The interleaved per-frame D/G rollout with a temporal D,
        batch sharded over the 8-device 'data' mesh — the framework's
        most complex multi-device path (VERDICT r2 #4; ref:
        imaginaire/trainers/vid2vid.py:238-288)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from imaginaire_tpu.parallel.mesh import create_mesh, get_mesh, set_mesh

        old = get_mesh()
        try:
            mesh = create_mesh(("data",))
            set_mesh(mesh)
            cfg = Config(CFG)
            cfg.logdir = str(tmp_path)
            trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
            n = mesh.devices.size
            batch = {
                "images": jnp.asarray(
                    rng.rand(n, 3, 64, 64, 3).astype(np.float32)) * 2 - 1,
                "label": jnp.asarray(
                    (rng.rand(n, 3, 64, 64, 12) > 0.9).astype(np.float32)),
            }
            trainer.init_state(jax.random.PRNGKey(0), batch)
            trainer.state = jax.device_put(trainer.state,
                                           NamedSharding(mesh, P()))
            batch = jax.device_put(batch, NamedSharding(mesh, P("data")))
            with mesh:
                batch = trainer.start_of_iteration(batch, 1)
                g = trainer.gen_update(batch)  # per-frame D updates inside
            for name, v in g.items():
                assert np.isfinite(float(jax.device_get(v))), name
            assert any(k.startswith("GAN_T") for k in g), g.keys()
        finally:
            set_mesh(old)


@pytest.mark.slow
class TestRolloutScan:
    """trainer.rollout_scan: the steady-state tail of the interleaved
    rollout runs as one lax.scan program (trainers/vid2vid.py::
    _rollout_tail_fn, SURVEY §7 hard-part #3). Same data + same seeds
    must give the same training result as the per-frame path."""

    def _run(self, scan, tmp_path, t=4):
        cfg = Config(CFG)
        cfg.logdir = str(tmp_path / ("scan" if scan else "loop"))
        cfg.trainer.rollout_scan = scan
        # shrink the perceptual graph: equivalence, not capacity
        cfg.trainer.perceptual_loss.layers = ["relu_1_1", "relu_2_1"]
        cfg.trainer.perceptual_loss.weights = [0.5, 1.0]
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = video_batch(np.random.RandomState(7), t=t)
        trainer.init_state(jax.random.PRNGKey(0), data)
        losses = trainer.gen_update(data)
        leaf = jax.tree_util.tree_leaves(
            trainer.state["vars_G"]["params"])[0]
        return ({k: float(jax.device_get(v)) for k, v in losses.items()},
                np.asarray(jax.device_get(leaf)))

    def test_scan_matches_per_frame_path(self, tmp_path):
        losses_a, leaf_a = self._run(False, tmp_path)
        losses_b, leaf_b = self._run(True, tmp_path)
        assert set(losses_a) == set(losses_b)
        for k in losses_a:
            np.testing.assert_allclose(losses_b[k], losses_a[k],
                                       rtol=2e-3, atol=2e-4, err_msg=k)
        np.testing.assert_allclose(leaf_b, leaf_a, rtol=2e-3, atol=2e-4)

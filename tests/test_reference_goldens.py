"""Goldens against the ACTUAL reference implementation in /root/reference.

Unlike test_network_goldens.py (hand-built torch twins), these tests
import the reference's own modules on CPU torch as oracles, convert the
randomly-initialized reference weights into this framework's pytrees,
and pin forward / loss parity on identical inputs:

  - Conv2dBlock orders (CNA / NAC), weight norm none / weight / spectral
    (ref: imaginaire/layers/conv.py:59-91)
  - Res2dBlock with learned shortcut (ref: imaginaire/layers/residual.py:129-151)
  - SpatiallyAdaptiveNorm (SPADE) and AdaptiveNorm (AdaIN)
    (ref: imaginaire/layers/activation_norm.py:22-234)
  - PartialConv2dBlock (ref: imaginaire/layers/conv.py:593-700)
  - Full SPADEGenerator + StyleEncoder forward
    (ref: imaginaire/generators/spade.py:401-493, 496-563)
  - Full SPADE Discriminator (FPSE + patch) forward and hinge-GAN /
    feature-matching / KL loss values (ref: imaginaire/discriminators/
    spade.py:73-117, losses/gan.py, feature_matching.py, kl.py)
  - Full pix2pixHD GlobalGenerator (ref: generators/pix2pixHD.py:240-275)
  - Full FUNIT translator: content/style encoders + MLP + AdaIN decoder
    with up-res blocks (ref: generators/funit.py:69-398)
  - Full MUNIT autoencoder reconstruction (ref: generators/munit.py:159-421)
  - Full UNIT autoencoder reconstruction (ref: generators/unit.py:91-300)
  - Full COCO-FUNIT translator incl. universal style bias + content-gated
    style fusion (ref: generators/coco_funit.py:71-194)

The vid2vid / fs-vid2vid / wc-vid2vid reference generators import the
CUDA third_party ops at module import time and cannot be loaded on CPU
torch; those families are covered by the hand-built FlowNet2/resample
goldens (test_network_goldens.py, test_flownet2.py) plus the learning
tier instead.

Import shims (albumentations; torch.Tensor.cuda as a CPU no-op for the
generator's ``self.xy.cuda()``) only unblock imports — they change no math.

Known, documented convention differences are scoped OUT of these goldens
rather than papered over:
  - nearest-resize index convention for label maps: goldens feed label
    maps that are piecewise-constant on 16x16-aligned blocks, so every
    power-of-two nearest resize agrees under either convention. (The
    resize convention itself is covered by the reference recipes only at
    block granularity; sub-block indexing may differ.)
"""

from __future__ import annotations

import importlib.util
import sys
import types

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")
tnn = torch.nn

REF_ROOT = "/root/reference"

# ---------------------------------------------------------------- import rig


def _load_ref():
    import os

    if not os.path.isdir(REF_ROOT):
        pytest.skip("reference checkout not available")
    if "albumentations" not in sys.modules:
        sys.modules["albumentations"] = types.ModuleType("albumentations")
    if REF_ROOT not in sys.path:
        sys.path.insert(0, REF_ROOT)
    # SPADEGenerator.__init__ unconditionally calls ``self.xy.cuda()``
    # (generators/spade.py:399); make .cuda a no-op on CPU-only torch.
    torch.Tensor.cuda = lambda self, *a, **k: self
    tnn.Module.cuda = lambda self, *a, **k: self

    import imaginaire.layers as ref_layers
    import imaginaire.discriminators.spade as ref_dis_spade
    import imaginaire.generators.spade as ref_gen_spade

    return ref_layers, ref_gen_spade, ref_dis_spade


def _load_ref_loss(stem):
    """Load a reference loss module standalone (dodges losses/__init__,
    which drags in torchvision-dependent perceptual + CUDA flow)."""
    spec = importlib.util.spec_from_file_location(
        f"ref_loss_{stem}", f"{REF_ROOT}/imaginaire/losses/{stem}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ref():
    return _load_ref()


# ------------------------------------------------------------- converters


def t2j(t):
    # copy=True: .numpy() aliases torch storage, and jax's CPU asarray
    # can alias the numpy buffer in turn — without the copy, torch's
    # in-place spectral-norm power iteration during the oracle forward
    # would silently mutate the converted u inside our variables
    return np.array(t.detach().cpu().numpy(), copy=True)


def _tr_conv(w):
    # torch (O, I, kh, kw) -> flax (kh, kw, I, O)
    return t2j(w).transpose(2, 3, 1, 0)


def _tr_linear(w):
    return t2j(w).transpose(1, 0)


def convert_torch_conv(tconv):
    """torch Conv2d/Linear (possibly spectral-/weight-normed) ->
    (params_dict, u_or_None) in this framework's layout."""
    is_linear = isinstance(tconv, tnn.Linear)
    tr = _tr_linear if is_linear else _tr_conv
    out, u = {}, None
    if hasattr(tconv, "weight_orig"):  # torch spectral_norm
        out["kernel"] = tr(tconv.weight_orig)
        u = t2j(tconv.weight_u)
    elif hasattr(tconv, "weight_g"):  # torch weight_norm
        out["kernel"] = tr(tconv.weight_v)
        out["g"] = t2j(tconv.weight_g).reshape(-1)
    else:
        out["kernel"] = tr(tconv.weight)
    if tconv.bias is not None:
        out["bias"] = t2j(tconv.bias)
    return out, u


def convert_norm(tnorm):
    """Instance/Batch norm params -> my InstanceNorm/BatchNorm trees."""
    params, stats = {}, {}
    if tnorm is None:
        return params, stats
    if isinstance(tnorm, tnn.modules.batchnorm._BatchNorm):
        params = {"scale": t2j(tnorm.weight), "bias": t2j(tnorm.bias)}
        stats = {"mean": t2j(tnorm.running_mean), "var": t2j(tnorm.running_var)}
    elif isinstance(tnorm, tnn.modules.instancenorm._InstanceNorm):
        if tnorm.affine:
            params = {"scale": t2j(tnorm.weight), "bias": t2j(tnorm.bias)}
    else:
        raise NotImplementedError(type(tnorm))
    return params, stats


def convert_spade_norm(tnorm):
    """ref SpatiallyAdaptiveNorm -> my SpatiallyAdaptiveNorm subtree.

    Returns (params, spectral). Handles both separate_projection modes.
    """
    params, spectral = {}, {}
    if tnorm.separate_projection:
        for i, (mlp, gam, bet) in enumerate(
                zip(tnorm.mlps, tnorm.gammas, tnorm.betas)):
            if len(mlp) > 0:
                p, u = convert_torch_conv(mlp[0].layers["conv"])
                params[f"mlp_{i}"] = {"conv": p}
                if u is not None:
                    spectral[f"mlp_{i}"] = {"conv": {"u": u}}
            p, u = convert_torch_conv(gam.layers["conv"])
            params[f"gamma_{i}"] = {"conv": p}
            if u is not None:
                spectral[f"gamma_{i}"] = {"conv": {"u": u}}
            p, u = convert_torch_conv(bet.layers["conv"])
            params[f"beta_{i}"] = {"conv": p}
            if u is not None:
                spectral[f"beta_{i}"] = {"conv": {"u": u}}
    else:
        for i, mlp in enumerate(tnorm.mlps):
            blocks = list(mlp)
            if len(blocks) == 2:  # hidden conv + gb conv
                p, u = convert_torch_conv(blocks[0].layers["conv"])
                params[f"mlp_{i}"] = {"conv": p}
                if u is not None:
                    spectral[f"mlp_{i}"] = {"conv": {"u": u}}
            p, u = convert_torch_conv(blocks[-1].layers["conv"])
            params[f"gb_{i}"] = {"conv": p}
            if u is not None:
                spectral[f"gb_{i}"] = {"conv": {"u": u}}
    return params, spectral


def convert_adaptive_norm(tnorm):
    """ref AdaptiveNorm -> my AdaptiveNorm subtree (linear projection)."""
    params, spectral = {}, {}

    def put(tlin_block, name):
        p, u = convert_torch_conv(tlin_block.layers["conv"])
        params[name] = p
        if u is not None:
            spectral[name] = {"u": u}

    if tnorm.separate_projection:
        put(tnorm.fc_gamma, "fc_gamma")
        put(tnorm.fc_beta, "fc_beta")
    else:
        put(tnorm.fc, "fc")
    return params, spectral


def convert_conv_block(tblock):
    """ref _BaseConvBlock (conv flavor) -> (params, spectral, batch_stats)
    for my Conv2dBlock / LinearBlock-in-conv-naming."""
    params, spectral, bstats = {}, {}, {}
    layers = tblock.layers
    conv = layers["conv"]
    is_linear = isinstance(conv, tnn.Linear) or (
        hasattr(conv, "weight_orig") and conv.weight_orig.dim() == 2) or (
        hasattr(conv, "weight_v") and conv.weight_v.dim() == 2)
    p, u = convert_torch_conv(conv)
    if is_linear:
        # my LinearBlock keeps kernel/bias (+ u) at block level
        params.update(p)
        if u is not None:
            spectral["u"] = u
    else:
        params["conv"] = p
        if u is not None:
            spectral["conv"] = {"u": u}
    if "norm" in layers:
        tnorm = layers["norm"]
        from imaginaire.layers.activation_norm import (  # noqa: F401
            AdaptiveNorm, SpatiallyAdaptiveNorm)

        if isinstance(tnorm, SpatiallyAdaptiveNorm):
            np_, ns = convert_spade_norm(tnorm)
            params["norm"] = np_
            if ns:
                spectral["norm"] = ns
            bn, bs = convert_norm(tnorm.norm)
            # SPADE base norm is affine=False -> no params; batch stats
            # live under the flax BatchNorm_0 inside my norm module.
            if bs:
                bstats["norm"] = {"BatchNorm_0": bs}
        elif isinstance(tnorm, AdaptiveNorm):
            np_, ns = convert_adaptive_norm(tnorm)
            params["norm"] = np_
            if ns:
                spectral["norm"] = ns
        else:
            bn, bs = convert_norm(tnorm)
            if bn:
                params["norm"] = bn
            if bs:
                bstats["norm"] = {"BatchNorm_0": bs}
    return params, spectral, bstats


def convert_res_block(tblock):
    """ref _BaseResBlock -> (params, spectral, batch_stats) for my
    _BaseResBlock (conv_block_0/1/s -> conv_0/1/s)."""
    params, spectral, bstats = {}, {}, {}
    mapping = {"conv_block_0": "conv_0", "conv_block_1": "conv_1"}
    if tblock.learn_shortcut:
        mapping["conv_block_s"] = "conv_s"
    for tname, jname in mapping.items():
        p, s, b = convert_conv_block(getattr(tblock, tname))
        params[jname] = p
        if s:
            spectral[jname] = s
        if b:
            bstats[jname] = b
    return params, spectral, bstats


def _merge_variables(init_vars, params, spectral, bstats=None):
    """Replace init-time leaves with converted ones, checking shapes."""
    import flax

    out = flax.core.unfreeze(init_vars)

    def merge(dst, src, path):
        for k, v in src.items():
            assert k in dst, f"missing {'/'.join(path + [k])} in init tree: {list(dst)}"
            if isinstance(v, dict):
                merge(dst[k], v, path + [k])
            else:
                assert tuple(dst[k].shape) == tuple(np.shape(v)), (
                    f"shape mismatch at {'/'.join(path + [k])}: "
                    f"{dst[k].shape} vs {np.shape(v)}")
                dst[k] = jax.numpy.asarray(v, dtype=dst[k].dtype)

    merge(out["params"], params, ["params"])
    if spectral:
        merge(out["spectral"], spectral, ["spectral"])
    if bstats:
        merge(out.get("batch_stats", {}), bstats, ["batch_stats"])
    return out


def nchw(x_nhwc):
    return torch.from_numpy(np.ascontiguousarray(x_nhwc.transpose(0, 3, 1, 2)))


def to_nhwc(t):
    return t2j(t).transpose(0, 2, 3, 1)


def _block_seg(rng, b, h, w, c, block=16):
    """Label map piecewise-constant on (block x block) tiles, so nearest
    resizes by powers of two agree across frameworks (see module docs)."""
    coarse = (rng.rand(b, h // block, w // block, c) > 0.7).astype(np.float32)
    return np.repeat(np.repeat(coarse, block, axis=1), block, axis=2)


TOL = dict(rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------- layer tier


class TestConvBlockGoldens:
    @pytest.mark.parametrize("order,wnorm,anorm", [
        ("CNA", "none", "instance"),
        ("NAC", "none", "instance"),
        ("CNA", "weight", "instance"),
        ("CNA", "spectral", "none"),
        ("NAC", "spectral", "instance"),
    ])
    def test_conv2d_block(self, ref, order, wnorm, anorm):
        ref_layers, _, _ = ref
        from imaginaire_tpu.layers import Conv2dBlock

        torch.manual_seed(0)
        tb = ref_layers.Conv2dBlock(
            5, 7, 3, stride=1, padding=1, weight_norm_type=wnorm,
            activation_norm_type=anorm, nonlinearity="leakyrelu",
            order=order)
        tb.train()  # torch spectral norm power-iterates in train mode
        jb = Conv2dBlock(7, kernel_size=3, stride=1, padding=1,
                         weight_norm_type="" if wnorm == "none" else wnorm,
                         activation_norm_type="" if anorm == "none" else anorm,
                         nonlinearity="leakyrelu", order=order)
        rng = np.random.RandomState(1)
        x = rng.randn(2, 8, 8, 5).astype(np.float32)
        variables = jb.init(jax.random.PRNGKey(0), x, training=True)
        p, s, b = convert_conv_block(tb)
        variables = _merge_variables(variables, p, s, b)
        want = to_nhwc(tb(nchw(x)))
        got, _ = jb.apply(variables, x, training=True,
                          mutable=["spectral", "batch_stats"])
        np.testing.assert_allclose(np.asarray(got), want, **TOL)

    def test_linear_block(self, ref):
        ref_layers, _, _ = ref
        from imaginaire_tpu.layers import LinearBlock

        torch.manual_seed(1)
        tb = ref_layers.LinearBlock(6, 9, weight_norm_type="spectral",
                                    nonlinearity="relu", order="CAN")
        tb.train()
        jb = LinearBlock(9, weight_norm_type="spectral",
                         nonlinearity="relu", order="CAN")
        rng = np.random.RandomState(2)
        x = rng.randn(3, 6).astype(np.float32)
        variables = jb.init(jax.random.PRNGKey(0), x, training=True)
        p, s, b = convert_conv_block(tb)
        variables = _merge_variables(variables, p, s, b)
        want = t2j(tb(torch.from_numpy(x)))
        got, _ = jb.apply(variables, x, training=True, mutable=["spectral"])
        np.testing.assert_allclose(np.asarray(got), want, **TOL)

    def test_res2d_block_learned_shortcut(self, ref):
        ref_layers, _, _ = ref
        from imaginaire_tpu.layers import Res2dBlock

        torch.manual_seed(2)
        tb = ref_layers.Res2dBlock(4, 6, 3, weight_norm_type="spectral",
                                   activation_norm_type="instance",
                                   nonlinearity="leakyrelu", order="CNACNA")
        tb.train()
        jb = Res2dBlock(6, kernel_size=3, weight_norm_type="spectral",
                        activation_norm_type="instance", order="CNACNA",
                        nonlinearity="leakyrelu")
        rng = np.random.RandomState(3)
        x = rng.randn(2, 8, 8, 4).astype(np.float32)
        variables = jb.init(jax.random.PRNGKey(0), x, training=True)
        p, s, b = convert_res_block(tb)
        variables = _merge_variables(variables, p, s, b)
        want = to_nhwc(tb(nchw(x)))
        got, _ = jb.apply(variables, x, training=True,
                          mutable=["spectral", "batch_stats"])
        np.testing.assert_allclose(np.asarray(got), want, **TOL)

    def test_partial_conv2d_block(self, ref):
        ref_layers, _, _ = ref
        from imaginaire_tpu.layers.conv import PartialConv2dBlock

        torch.manual_seed(3)
        tb = ref_layers.PartialConv2dBlock(4, 6, 3, stride=1, padding=1,
                                           nonlinearity="relu")
        tb.eval()
        jb = PartialConv2dBlock(6, kernel_size=3, stride=1,
                                nonlinearity="relu")
        rng = np.random.RandomState(4)
        x = rng.randn(2, 8, 8, 4).astype(np.float32)
        mask = (rng.rand(2, 8, 8, 1) > 0.4).astype(np.float32)
        variables = jb.init(jax.random.PRNGKey(0), x, mask_in=mask)
        p, s, b = convert_conv_block(tb)
        variables = _merge_variables(variables, p, s, b)
        want = tb(nchw(x), mask_in=nchw(mask))
        if isinstance(want, tuple):
            want = want[0]
        want = to_nhwc(want)
        got, _ = jb.apply(variables, x, mask_in=mask)
        np.testing.assert_allclose(np.asarray(got), want, **TOL)


class TestNormGoldens:
    @pytest.mark.parametrize("separate", [True, False])
    def test_spatially_adaptive_norm(self, ref, separate):
        from imaginaire.layers.activation_norm import SpatiallyAdaptiveNorm as TNorm

        from imaginaire_tpu.layers.activation_norm import SpatiallyAdaptiveNorm

        torch.manual_seed(4)
        tn = TNorm(6, 5, num_filters=8, kernel_size=3,
                   separate_projection=separate,
                   activation_norm_type="instance")
        tn.train()
        jn = SpatiallyAdaptiveNorm(num_filters=8, kernel_size=3,
                                   base_norm="instance",
                                   separate_projection=separate)
        rng = np.random.RandomState(5)
        x = rng.randn(2, 16, 16, 6).astype(np.float32)
        # full-res cond: no resize happens, so any values are safe here
        cond = rng.randn(2, 16, 16, 5).astype(np.float32)
        variables = jn.init(jax.random.PRNGKey(0), x, cond)
        p, s = convert_spade_norm(tn)
        variables = _merge_variables(variables, p, s)
        want = to_nhwc(tn(nchw(x), nchw(cond)))
        got = jn.apply(variables, x, cond)
        np.testing.assert_allclose(np.asarray(got), want, **TOL)

    def test_spade_sync_batch_base_train_mode(self, ref):
        """sync_batch base norm in training mode: batch-stats path."""
        from imaginaire.layers.activation_norm import SpatiallyAdaptiveNorm as TNorm

        from imaginaire_tpu.layers.activation_norm import SpatiallyAdaptiveNorm

        torch.manual_seed(5)
        tn = TNorm(6, 5, num_filters=0, kernel_size=3,
                   separate_projection=False,
                   activation_norm_type="sync_batch")
        tn.train()
        jn = SpatiallyAdaptiveNorm(num_filters=0, kernel_size=3,
                                   base_norm="sync_batch",
                                   separate_projection=False)
        rng = np.random.RandomState(6)
        x = rng.randn(4, 8, 8, 6).astype(np.float32)
        cond = rng.randn(4, 8, 8, 5).astype(np.float32)
        variables = jn.init(jax.random.PRNGKey(0), x, cond, training=True)
        p, s = convert_spade_norm(tn)
        variables = _merge_variables(variables, p, s)
        want = to_nhwc(tn(nchw(x), nchw(cond)))
        got, _ = jn.apply(variables, x, cond, training=True,
                          mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(got), want, **TOL)

    @pytest.mark.parametrize("separate", [True, False])
    def test_adaptive_norm(self, ref, separate):
        from imaginaire.layers.activation_norm import AdaptiveNorm as TNorm

        from imaginaire_tpu.layers.activation_norm import AdaptiveNorm

        torch.manual_seed(6)
        tn = TNorm(6, 10, separate_projection=separate,
                   activation_norm_type="instance")
        tn.train()
        jn = AdaptiveNorm(base_norm="instance", separate_projection=separate)
        rng = np.random.RandomState(7)
        x = rng.randn(2, 8, 8, 6).astype(np.float32)
        cond = rng.randn(2, 10).astype(np.float32)
        variables = jn.init(jax.random.PRNGKey(0), x, cond)
        p, s = convert_adaptive_norm(tn)
        variables = _merge_variables(variables, p, s)
        want = to_nhwc(tn(nchw(x), torch.from_numpy(cond)))
        got = jn.apply(variables, x, cond)
        np.testing.assert_allclose(np.asarray(got), want, **TOL)


# ------------------------------------------------------------- model tier


def _build_ref_spade_generator(ref_gen_spade, nf, num_labels, style_dims):
    import types as _t

    anp = _t.SimpleNamespace(
        num_filters=8, kernel_size=3, weight_norm_type="spectral",
        separate_projection=False, activation_norm_type="instance",
        cond_dims=num_labels,  # the ref Generator wrapper injects this
        activation_norm_params=_t.SimpleNamespace(affine=False))
    return ref_gen_spade.SPADEGenerator(
        num_labels=num_labels,
        out_image_small_side_size=256,
        image_channels=3,
        num_filters=nf,
        kernel_size=3,
        style_dims=style_dims,
        activation_norm_params=anp,
        weight_norm_type="spectral",
        global_adaptive_norm_type="instance",
        skip_activation_norm=True,
        use_posenc_in_input_layer=True,
        use_style_encoder=True)


def convert_spade_generator(tgen):
    from imaginaire.layers import Conv2dBlock as TConv
    from imaginaire.layers import LinearBlock as TLin
    from imaginaire.layers import Res2dBlock as TRes

    params, spectral, bstats = {}, {}, {}
    for name, mod in tgen.named_children():
        if isinstance(mod, TRes):
            p, s, b = convert_res_block(mod)
        elif isinstance(mod, (TConv, TLin)):
            p, s, b = convert_conv_block(mod)
        else:
            continue
        params[name] = p
        if s:
            spectral[name] = s
        if b:
            bstats[name] = b
    return params, spectral, bstats


class TestSpadeGeneratorGolden:
    def test_forward_matches_reference(self, ref):
        _, ref_gen_spade, _ = ref
        from imaginaire_tpu.models.generators.spade import SPADEGenerator

        nf, num_labels, style_dims = 4, 5, 8
        torch.manual_seed(7)
        tgen = _build_ref_spade_generator(ref_gen_spade, nf, num_labels,
                                          style_dims)
        tgen.train()
        anp = {"num_filters": 8, "kernel_size": 3,
               "weight_norm_type": "spectral",
               "separate_projection": False,
               "activation_norm_type": "instance"}
        jgen = SPADEGenerator(
            num_labels=num_labels, out_image_small_side_size=256,
            image_channels=3, num_filters=nf, kernel_size=3,
            style_dims=style_dims, activation_norm_params=anp,
            weight_norm_type="spectral",
            global_adaptive_norm_type="instance",
            skip_activation_norm=True, use_posenc_in_input_layer=True,
            use_style_encoder=True)

        rng = np.random.RandomState(8)
        seg = _block_seg(rng, 2, 256, 256, num_labels)
        z = rng.randn(2, style_dims).astype(np.float32)

        variables = jgen.init(jax.random.PRNGKey(0), seg, z, training=True)
        p, s, b = convert_spade_generator(tgen)
        variables = _merge_variables(variables, p, s, b)
        want = to_nhwc(tgen({"label": nchw(seg), "z": torch.from_numpy(z)})
                       ["fake_images"])
        got, _ = jgen.apply(variables, seg, z, training=True,
                            mutable=["spectral", "batch_stats"])
        got = np.asarray(got["fake_images"])
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)

    def test_style_encoder_matches_reference(self, ref):
        _, ref_gen_spade, _ = ref
        from imaginaire_tpu.models.generators.spade import StyleEncoder

        import types as _t

        torch.manual_seed(8)
        tenc = ref_gen_spade.StyleEncoder(_t.SimpleNamespace(
            input_image_channels=3, num_filters=4, kernel_size=3,
            style_dims=8, weight_norm_type="spectral", freeze_random=False))
        tenc.train()
        jenc = StyleEncoder(num_filters=4, kernel_size=3, style_dims=8,
                            weight_norm_type="spectral")
        rng = np.random.RandomState(9)
        x = rng.randn(2, 256, 256, 3).astype(np.float32)
        variables = jenc.init(
            {"params": jax.random.PRNGKey(0), "noise": jax.random.PRNGKey(1)},
            x, training=True)
        params, spectral, bstats = {}, {}, {}
        for name in ["layer1", "layer2", "layer3", "layer4", "layer5",
                     "layer6", "fc_mu", "fc_var"]:
            p, s, b = convert_conv_block(getattr(tenc, name))
            if name.startswith("fc_"):
                # the encoder flattens (C,H,W) in torch but (H,W,C) here;
                # reindex the fc input dimension accordingly
                k = p["kernel"]  # (C*H*W, out) in torch input order
                c, h, w = 4 * 8, 4, 4
                p["kernel"] = (k.reshape(c, h, w, -1)
                                .transpose(1, 2, 0, 3)
                                .reshape(c * h * w, -1))
            params[name] = p
            if s:
                spectral[name] = s
        variables = _merge_variables(variables, params, spectral)
        tmu, tlogvar, _ = tenc(nchw(x))
        (mu, logvar, _), _ = jenc.apply(
            variables, x, training=True, rngs={"noise": jax.random.PRNGKey(2)},
            mutable=["spectral"])
        np.testing.assert_allclose(np.asarray(mu), t2j(tmu), **TOL)
        np.testing.assert_allclose(np.asarray(logvar), t2j(tlogvar), **TOL)

        # KL loss value parity on the matched mu/logvar
        from imaginaire_tpu.losses.kl import gaussian_kl_loss

        ref_kl = _load_ref_loss("kl").GaussianKLLoss()
        want = float(ref_kl(tmu, tlogvar))
        got = float(gaussian_kl_loss(np.asarray(mu), np.asarray(logvar)))
        np.testing.assert_allclose(got, want, rtol=1e-4)


# ------------------------------------------------------- discriminator tier


class TestSpadeDiscriminatorGolden:
    def _build(self, ref, num_labels=5, nf=4):
        _, _, ref_dis_spade = ref
        import types as _t

        from imaginaire_tpu.models.discriminators.spade import Discriminator

        dis_cfg = _t.SimpleNamespace(
            kernel_size=3, num_filters=nf, max_num_filters=4 * nf,
            num_discriminators=2, num_layers=2, activation_norm_type="none",
            weight_norm_type="spectral")
        data_cfg = _t.SimpleNamespace(
            type="imaginaire.datasets.paired_images",
            input_types=[
                {"images": _t.SimpleNamespace(num_channels=3)},
                {"seg_maps": _t.SimpleNamespace(num_channels=num_labels)},
            ],
            input_image=["images"], input_labels=["seg_maps"])
        torch.manual_seed(9)
        tdis = ref_dis_spade.Discriminator(dis_cfg, data_cfg)
        tdis.train()

        jdis_cfg = {"kernel_size": 3, "num_filters": nf,
                    "max_num_filters": 4 * nf, "num_discriminators": 2,
                    "num_layers": 2, "activation_norm_type": "none",
                    "weight_norm_type": "spectral"}
        jdata_cfg = {
            "type": "imaginaire_tpu.data.paired_images",
            "input_types": [
                {"images": {"num_channels": 3}},
                {"seg_maps": {"num_channels": num_labels}},
            ],
            "input_image": ["images"], "input_labels": ["seg_maps"]}
        jdis = Discriminator(jdis_cfg, jdata_cfg)
        return tdis, jdis

    def _convert(self, tdis):
        params, spectral = {}, {}
        # FPSE: enc/lat/final/output/seg/embedding conv blocks
        fp, fs = {}, {}
        fpse = tdis.fpse_discriminator
        for tname, jname in [
                ("enc1", "enc1"), ("enc2", "enc2"), ("enc3", "enc3"),
                ("enc4", "enc4"), ("enc5", "enc5"),
                ("lat2", "lat2"), ("lat3", "lat3"), ("lat4", "lat4"),
                ("lat5", "lat5"),
                ("final2", "final2"), ("final3", "final3"),
                ("final4", "final4"),
                ("output", "output"), ("seg", "seg"),
                ("embedding", "embedding")]:
            p, s, _ = convert_conv_block(getattr(fpse, tname))
            fp[jname] = p
            if s:
                fs[jname] = s
        params["fpse"] = fp
        if fs:
            spectral["fpse"] = fs
        for i, td in enumerate(tdis.discriminators):
            dp, ds = {}, {}
            n_layer_blocks = len([n for n, _ in td.named_children()])
            for li in range(n_layer_blocks):
                seq = getattr(td, f"layer{li}")
                p, s, _ = convert_conv_block(seq[0])
                dp[f"layer{li}"] = p
                if s:
                    ds[f"layer{li}"] = s
            params[f"patch_d_{i}"] = dp
            if ds:
                spectral[f"patch_d_{i}"] = ds
        return params, spectral

    def test_forward_and_losses_match(self, ref):
        tdis, jdis = self._build(ref)
        num_labels = 5
        rng = np.random.RandomState(10)
        seg = _block_seg(rng, 2, 64, 64, num_labels)
        real = rng.randn(2, 64, 64, 3).astype(np.float32) * 0.5
        fake = rng.randn(2, 64, 64, 3).astype(np.float32) * 0.5

        data_j = {"label": seg, "images": real}
        out_j = {"fake_images": fake}
        variables = jdis.init(jax.random.PRNGKey(0), data_j, out_j,
                              training=True)
        p, s = self._convert(tdis)
        variables = _merge_variables(variables, p, s)
        got, _ = jdis.apply(variables, data_j, out_j, training=True,
                            mutable=["spectral"])

        data_t = {"label": nchw(seg), "images": nchw(real)}
        out_t = {"fake_images": nchw(fake)}
        want = tdis(data_t, out_t)

        for key in ["real_outputs", "fake_outputs"]:
            assert len(got[key]) == len(want[key])
            for g, w in zip(got[key], want[key]):
                np.testing.assert_allclose(
                    np.asarray(g), to_nhwc(w), rtol=2e-3, atol=2e-4)

        # hinge GAN loss (D and G forms) + feature matching parity
        from imaginaire_tpu.losses.gan import gan_loss
        from imaginaire_tpu.losses.feature_matching import feature_matching_loss

        ref_gan = _load_ref_loss("gan").GANLoss("hinge")
        ref_fm = _load_ref_loss("feature_matching").FeatureMatchingLoss()

        pairs = [
            (float(gan_loss(got["real_outputs"], True, "hinge", True)),
             float(ref_gan(want["real_outputs"], True, dis_update=True))),
            (float(gan_loss(got["fake_outputs"], False, "hinge", True)),
             float(ref_gan(want["fake_outputs"], False, dis_update=True))),
            (float(gan_loss(got["fake_outputs"], True, "hinge", False)),
             float(ref_gan(want["fake_outputs"], True, dis_update=False))),
            (float(feature_matching_loss(got["fake_features"],
                                         got["real_features"])),
             float(ref_fm(want["fake_features"], want["real_features"]))),
        ]
        for got_v, want_v in pairs:
            np.testing.assert_allclose(got_v, want_v, rtol=2e-3, atol=2e-4)


# ----------------------------------------------------- pix2pixHD tier


class TestPix2pixHDGlobalGolden:
    """Full pix2pixHD GlobalGenerator forward against the reference's
    Sequential (ref: imaginaire/generators/pix2pixHD.py:240-275),
    weight-converted index-by-index."""

    def _build_ref(self, num_labels, nf, nd, nr):
        import functools
        import types as _t

        from imaginaire.generators import pix2pixHD as ref_p2p
        from imaginaire.layers import Conv2dBlock as TConv
        from imaginaire.layers import Res2dBlock as TRes

        base_conv_block = functools.partial(
            TConv, padding_mode="reflect", weight_norm_type="",
            activation_norm_type="instance", activation_norm_params=None,
            nonlinearity="relu")
        base_res_block = functools.partial(
            TRes, padding_mode="reflect", weight_norm_type="",
            activation_norm_type="instance", activation_norm_params=None,
            nonlinearity="relu", order="CNACN")
        gen_cfg = _t.SimpleNamespace(num_filters=nf, num_downsamples=nd,
                                     num_res_blocks=nr)
        data_cfg = _t.SimpleNamespace(
            type="imaginaire.datasets.paired_images",
            input_types=[{"images": _t.SimpleNamespace(num_channels=3)},
                         {"seg_maps": _t.SimpleNamespace(
                             num_channels=num_labels)}],
            input_image=["images"], input_labels=["seg_maps"])
        return ref_p2p.GlobalGenerator(gen_cfg, data_cfg, num_labels,
                                       "reflect", base_conv_block,
                                       base_res_block)

    def _convert(self, tglobal, nd, nr):
        params, bstats = {}, {}
        seq = list(tglobal.model)
        k = 0

        def put_conv(name, mod):
            p, s, b = convert_conv_block(mod)
            params[name] = p
            if b:
                bstats[name] = b

        put_conv("conv_in", seq[k]); k += 1
        for i in range(nd):
            put_conv(f"down_{i}", seq[k]); k += 1
        for i in range(nr):
            p, s, b = convert_res_block(seq[k])
            params[f"res_{i}"] = p
            k += 1
        for i in reversed(range(nd)):
            k += 1  # NearestUpsample module — no params
            put_conv(f"up_{i}", seq[k]); k += 1
        put_conv("conv_out", seq[k])
        return params, bstats

    def test_global_generator_matches_reference(self, ref):
        from imaginaire_tpu.models.generators.pix2pixHD import GlobalGenerator

        num_labels, nf, nd, nr = 5, 4, 2, 3
        torch.manual_seed(10)
        tg = self._build_ref(num_labels, nf, nd, nr)
        tg.train()
        jg = GlobalGenerator(num_filters=nf, num_downsamples=nd,
                             num_res_blocks=nr, num_img_channels=3,
                             padding_mode="reflect", weight_norm_type="",
                             activation_norm_type="instance",
                             output_img=True)
        rng = np.random.RandomState(11)
        seg = _block_seg(rng, 2, 64, 64, num_labels)
        variables = jg.init(jax.random.PRNGKey(0), seg, training=True)
        p, b = self._convert(tg, nd, nr)
        variables = _merge_variables(variables, p, {}, b)
        want = to_nhwc(tg(nchw(seg)))
        got = jg.apply(variables, seg, training=True)
        assert np.asarray(got).shape == want.shape
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=2e-3, atol=2e-4)


# Shared sequential-walk converters for the UNIT-family encoders/decoders
# (style enc: [conv7, downs..., AdaptiveAvgPool2d, 1x1 Conv2d]; content
# enc: [conv7, downs..., res...]; decoder ModuleList:
# [res..., (NearestUpsample, conv)... , conv_out]; MLP: LinearBlocks).


def _convert_style_encoder_seq(seq, n_down):
    se = {}
    se["conv_in"], _, _ = convert_conv_block(seq[0])
    for i in range(n_down):
        se[f"down_{i}"], _, _ = convert_conv_block(seq[1 + i])
    final = seq[-1]  # plain nn.Conv2d(nf, style, 1) on the pooled vec
    se["fc_out"] = {"kernel": t2j(final.weight)[:, :, 0, 0].T,
                    "bias": t2j(final.bias)}
    return se


def _convert_content_encoder_seq(seq, n_down, n_res):
    ce = {}
    ce["conv_in"], _, _ = convert_conv_block(seq[0])
    for i in range(n_down):
        ce[f"down_{i}"], _, _ = convert_conv_block(seq[1 + i])
    for i in range(n_res):
        p, _, _ = convert_res_block(seq[1 + n_down + i])
        ce[f"res_{i}"] = p
    return ce


def _convert_decoder_blocks(blocks, n_res, n_ups, upres):
    """``upres=True``: upsampling via UpRes2dBlocks (FUNIT); otherwise
    (NearestUpsample, Conv2dBlock) pairs (MUNIT/UNIT)."""
    de = {}
    k = 0
    for i in range(n_res):
        p, _, _ = convert_res_block(blocks[k])
        de[f"res_{i}"] = p
        k += 1
    for i in range(n_ups):
        if upres:
            p, _, _ = convert_res_block(blocks[k])
            de[f"up_{i}"] = p
            k += 1
        else:
            k += 1  # NearestUpsample — no params
            de[f"up_{i}"], _, _ = convert_conv_block(blocks[k])
            k += 1
    de["conv_out"], _, _ = convert_conv_block(blocks[k])
    return de


def _convert_mlp_seq(seq):
    ml = {}
    p, _, _ = convert_conv_block(seq[0])
    ml["fc_in"] = p
    for i in range(len(seq) - 2):
        p, _, _ = convert_conv_block(seq[1 + i])
        ml[f"fc_{i}"] = p
    p, _, _ = convert_conv_block(seq[-1])
    ml["fc_out"] = p
    return ml


# --------------------------------------------------------- FUNIT tier


class TestFunitGeneratorGolden:
    """Full FUNIT translator (content/style encoders + MLP + AdaIN
    decoder with up-res blocks) against the reference
    (ref: imaginaire/generators/funit.py:69-398), weight-converted."""

    NF, NF_MLP, STYLE, NRB, NMLP, NDS, NDC = 8, 16, 8, 2, 3, 3, 2

    def _build_ref(self):
        import types as _t

        from imaginaire.generators import funit as ref_funit

        gen_cfg = _t.SimpleNamespace(
            num_filters=self.NF, num_filters_mlp=self.NF_MLP,
            style_dims=self.STYLE, num_res_blocks=self.NRB,
            num_mlp_blocks=self.NMLP, num_downsamples_style=self.NDS,
            num_downsamples_content=self.NDC, weight_norm_type="")
        return ref_funit.Generator(gen_cfg, None)

    def _convert(self, tgen):
        tr = tgen.generator
        params = {
            "style_encoder": _convert_style_encoder_seq(
                list(tr.style_encoder.model), self.NDS),
            "content_encoder": _convert_content_encoder_seq(
                list(tr.content_encoder.model), self.NDC, self.NRB),
            "decoder": _convert_decoder_blocks(
                list(tr.decoder.decoder), 2, self.NDC, upres=True),
            "mlp": _convert_mlp_seq(list(tr.mlp.model)),
        }
        return {"generator": params}

    def test_translator_matches_reference(self, ref):
        from imaginaire_tpu.models.generators.funit import Generator

        torch.manual_seed(12)
        tgen = self._build_ref()
        tgen.train()
        jgen = Generator({
            "num_filters": self.NF, "num_filters_mlp": self.NF_MLP,
            "style_dims": self.STYLE, "num_res_blocks": self.NRB,
            "num_mlp_blocks": self.NMLP,
            "num_downsamples_style": self.NDS,
            "num_downsamples_content": self.NDC,
            "weight_norm_type": ""})
        rng = np.random.RandomState(13)
        data_j = {
            "images_content": rng.randn(2, 64, 64, 3).astype(np.float32) * .5,
            "images_style": rng.randn(2, 64, 64, 3).astype(np.float32) * .5,
        }
        variables = jgen.init(jax.random.PRNGKey(0), data_j, training=True)
        variables = _merge_variables(variables, self._convert(tgen), {})
        data_t = {"images_content": nchw(data_j["images_content"]),
                  "images_style": nchw(data_j["images_style"])}
        want = tgen(data_t)
        got = jgen.apply(variables, data_j, training=True)
        for key in ("images_trans", "images_recon"):
            np.testing.assert_allclose(np.asarray(got[key]),
                                       to_nhwc(want[key]),
                                       rtol=2e-3, atol=2e-4, err_msg=key)


# --------------------------------------------------------- MUNIT tier


class TestMunitAutoEncoderGolden:
    """Full MUNIT autoencoder (style/content encoders + MLP + AdaIN
    decoder) reconstruction against the reference
    (ref: imaginaire/generators/munit.py:159-421), weight-converted."""

    NF, MAXF, NF_MLP, LATENT, NRB, NMLP, NDS, NDC = 8, 32, 16, 8, 2, 2, 3, 2

    def _build_ref(self):
        from imaginaire.generators import munit as ref_munit

        return ref_munit.AutoEncoder(
            num_filters=self.NF, max_num_filters=self.MAXF,
            num_filters_mlp=self.NF_MLP, latent_dim=self.LATENT,
            num_res_blocks=self.NRB, num_mlp_blocks=self.NMLP,
            num_downsamples_style=self.NDS,
            num_downsamples_content=self.NDC)

    def _convert(self, tae):
        return {
            "style_encoder": _convert_style_encoder_seq(
                list(tae.style_encoder.model), self.NDS),
            "content_encoder": _convert_content_encoder_seq(
                list(tae.content_encoder.model), self.NDC, self.NRB),
            "decoder": _convert_decoder_blocks(
                list(tae.decoder.decoder), self.NRB, self.NDC, upres=False),
            "mlp": _convert_mlp_seq(list(tae.mlp.model)),
        }

    def test_autoencoder_reconstruction_matches(self, ref):
        from imaginaire_tpu.models.generators.munit import AutoEncoder

        torch.manual_seed(14)
        tae = self._build_ref()
        tae.train()
        jae = AutoEncoder({
            "num_filters": self.NF, "max_num_filters": self.MAXF,
            "num_filters_mlp": self.NF_MLP, "latent_dim": self.LATENT,
            "num_res_blocks": self.NRB, "num_mlp_blocks": self.NMLP,
            "num_downsamples_style": self.NDS,
            "num_downsamples_content": self.NDC})
        rng = np.random.RandomState(15)
        x = rng.randn(2, 64, 64, 3).astype(np.float32) * 0.5
        variables = jae.init(jax.random.PRNGKey(0), x, training=True)
        variables = _merge_variables(variables, self._convert(tae), {})
        want = to_nhwc(tae(nchw(x)))
        got = jae.apply(variables, x, training=True)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=2e-3, atol=2e-4)


# ---------------------------------------------------------- UNIT tier


class TestUnitAutoEncoderGolden:
    """Full UNIT autoencoder reconstruction against the reference
    (ref: imaginaire/generators/unit.py:91-300), weight-converted."""

    NF, MAXF, NRB, NDC = 8, 32, 2, 2

    def _build_ref(self):
        from imaginaire.generators import unit as ref_unit

        return ref_unit.AutoEncoder(
            num_filters=self.NF, max_num_filters=self.MAXF,
            num_res_blocks=self.NRB, num_downsamples_content=self.NDC)

    def _convert(self, tae):
        return {
            "content_encoder": _convert_content_encoder_seq(
                list(tae.content_encoder.model), self.NDC, self.NRB),
            "decoder": _convert_decoder_blocks(
                list(tae.decoder.decoder), self.NRB, self.NDC, upres=False),
        }

    def test_autoencoder_reconstruction_matches(self, ref):
        from imaginaire_tpu.models.generators.unit import AutoEncoder

        torch.manual_seed(16)
        tae = self._build_ref()
        tae.train()
        jae = AutoEncoder({
            "num_filters": self.NF, "max_num_filters": self.MAXF,
            "num_res_blocks": self.NRB,
            "num_downsamples_content": self.NDC})
        rng = np.random.RandomState(17)
        x = rng.randn(2, 64, 64, 3).astype(np.float32) * 0.5
        variables = jae.init(jax.random.PRNGKey(0), x, training=True)
        variables = _merge_variables(variables, self._convert(tae), {})
        want = to_nhwc(tae(nchw(x)))
        got = jae.apply(variables, x, training=True)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=2e-3, atol=2e-4)


# ---------------------------------------------------- COCO-FUNIT tier


class TestCocoFunitGeneratorGolden(TestFunitGeneratorGolden):
    """COCO-FUNIT: FUNIT plus the universal style bias and the
    content-gated style fusion MLPs
    (ref: imaginaire/generators/coco_funit.py:71-194)."""

    USB = 16

    def _build_ref(self):
        import types as _t

        from imaginaire.generators import coco_funit as ref_coco

        gen_cfg = _t.SimpleNamespace(
            num_filters=self.NF, num_filters_mlp=self.NF_MLP,
            style_dims=self.STYLE, usb_dims=self.USB,
            num_res_blocks=self.NRB, num_mlp_blocks=self.NMLP,
            num_downsamples_style=self.NDS,
            num_downsamples_content=self.NDC, weight_norm_type="")
        return ref_coco.Generator(gen_cfg, None)

    def _convert(self, tgen):
        out = super()._convert(tgen)
        tr = tgen.generator
        params = out["generator"]
        params["usb"] = t2j(tr.usb)
        for name in ("mlp_content", "mlp_style"):
            params[name] = _convert_mlp_seq(list(getattr(tr, name).model))
        return out

    def test_translator_matches_reference(self, ref):
        from imaginaire_tpu.models.generators.coco_funit import Generator

        torch.manual_seed(18)
        tgen = self._build_ref()
        tgen.train()
        jgen = Generator({
            "num_filters": self.NF, "num_filters_mlp": self.NF_MLP,
            "style_dims": self.STYLE, "usb_dims": self.USB,
            "num_res_blocks": self.NRB, "num_mlp_blocks": self.NMLP,
            "num_downsamples_style": self.NDS,
            "num_downsamples_content": self.NDC,
            "weight_norm_type": ""})
        rng = np.random.RandomState(19)
        data_j = {
            "images_content": rng.randn(2, 64, 64, 3).astype(np.float32) * .5,
            "images_style": rng.randn(2, 64, 64, 3).astype(np.float32) * .5,
        }
        variables = jgen.init(jax.random.PRNGKey(0), data_j, training=True)
        variables = _merge_variables(variables, self._convert(tgen), {})
        data_t = {"images_content": nchw(data_j["images_content"]),
                  "images_style": nchw(data_j["images_style"])}
        want = tgen(data_t)
        got = jgen.apply(variables, data_j, training=True)
        for key in ("images_trans", "images_recon"):
            np.testing.assert_allclose(np.asarray(got[key]),
                                       to_nhwc(want[key]),
                                       rtol=2e-3, atol=2e-4, err_msg=key)


class TestFunitDiscriminatorGolden:
    """FUNIT projection discriminator (residual trunk + class-projection
    logits) against the reference
    (ref: imaginaire/discriminators/funit.py:52-119), weight-converted."""

    NF, MAXF, NL, NCLS = 8, 32, 3, 5

    def _build_ref(self):
        import types as _t

        from imaginaire.discriminators import funit as ref_dis

        dis_cfg = _t.SimpleNamespace(
            num_filters=self.NF, max_num_filters=self.MAXF,
            num_layers=self.NL, num_classes=self.NCLS,
            weight_norm_type="")
        return ref_dis.Discriminator(dis_cfg, None)

    def _convert(self, tdis):
        m = tdis.model
        params = {}
        seq = list(m.model)
        k = 0
        params["conv_in"], _, _ = convert_conv_block(seq[k]); k += 1
        for i in range(self.NL):
            p, _, _ = convert_res_block(seq[k]); k += 1
            params[f"res_{i}_0"] = p
            p, _, _ = convert_res_block(seq[k]); k += 1
            params[f"res_{i}_1"] = p
            if i != self.NL - 1:
                k += 2  # ReflectionPad2d + AvgPool2d — no params
        params["classifier"], _, _ = convert_conv_block(m.classifier)
        params["embedder"] = {"embedding": t2j(m.embedder.weight)}
        return {"model": params}

    def test_forward_matches_reference(self, ref):
        from imaginaire_tpu.models.discriminators.funit import Discriminator

        torch.manual_seed(20)
        tdis = self._build_ref()
        tdis.train()
        jdis = Discriminator({
            "num_filters": self.NF, "max_num_filters": self.MAXF,
            "num_layers": self.NL, "num_classes": self.NCLS,
            "weight_norm_type": ""})
        rng = np.random.RandomState(21)
        data_j = {
            "images_style": rng.randn(2, 32, 32, 3).astype(np.float32) * .5,
            "labels_style": np.array([1, 3], np.int32),
            "labels_content": np.array([0, 4], np.int32),
        }
        g_out_j = {
            "images_trans": rng.randn(2, 32, 32, 3).astype(np.float32) * .5,
            "images_recon": rng.randn(2, 32, 32, 3).astype(np.float32) * .5,
        }
        variables = jdis.init(jax.random.PRNGKey(0), data_j, g_out_j,
                              training=True)
        variables = _merge_variables(variables, self._convert(tdis), {})
        data_t = {"images_style": nchw(data_j["images_style"]),
                  "labels_style": torch.from_numpy(
                      data_j["labels_style"].astype(np.int64)),
                  "labels_content": torch.from_numpy(
                      data_j["labels_content"].astype(np.int64))}
        g_out_t = {"images_trans": nchw(g_out_j["images_trans"]),
                   "images_recon": nchw(g_out_j["images_recon"])}
        want = tdis(data_t, g_out_t)
        got = jdis.apply(variables, data_j, g_out_j, training=True)
        for key in ("fake_out_trans", "real_out_style", "fake_out_recon"):
            np.testing.assert_allclose(np.asarray(got[key]),
                                       to_nhwc(want[key]),
                                       rtol=2e-3, atol=2e-4, err_msg=key)
        for key in ("fake_features_trans", "real_features_style"):
            np.testing.assert_allclose(np.asarray(got[key]),
                                       t2j(want[key]),
                                       rtol=2e-3, atol=2e-4, err_msg=key)


class TestMultiResPatchDiscriminatorGolden:
    """Full 2-scale pyramid goldens for the standalone multires patch
    discriminators — plain and weight-shared — including the
    align-corners bilinear downsample between scales
    (ref: imaginaire/discriminators/multires_patch.py:103-242)."""

    NF, NL, ND = 4, 2, 2

    def _convert_patch_d(self, td):
        dp, ds = {}, {}
        n_blocks = len(list(td.named_children()))
        for li in range(n_blocks):
            seq = getattr(td, f"layer{li}")
            p, s, _ = convert_conv_block(seq[0])
            dp[f"layer{li}"] = p
            if s:
                ds[f"layer{li}"] = s
        return dp, ds

    @pytest.mark.parametrize("shared", [False, True])
    def test_pyramid_matches_reference(self, ref, shared):
        from imaginaire.discriminators import multires_patch as ref_mrp

        from imaginaire_tpu.models.discriminators.multires_patch import (
            MultiResPatchDiscriminator,
        )

        torch.manual_seed(22)
        cls = (ref_mrp.WeightSharedMultiResPatchDiscriminator if shared
               else ref_mrp.MultiResPatchDiscriminator)
        tdis = cls(num_discriminators=self.ND, kernel_size=3,
                   num_image_channels=3, num_filters=self.NF,
                   num_layers=self.NL, max_num_filters=4 * self.NF,
                   activation_norm_type="", weight_norm_type="spectral")
        tdis.train()
        jdis = MultiResPatchDiscriminator(
            num_discriminators=self.ND, kernel_size=3,
            num_filters=self.NF, num_layers=self.NL,
            max_num_filters=4 * self.NF, activation_norm_type="",
            weight_norm_type="spectral", weight_shared=shared)
        rng = np.random.RandomState(23)
        x = rng.randn(2, 32, 32, 3).astype(np.float32) * 0.5
        variables = jdis.init(jax.random.PRNGKey(0), x, training=True)
        params, spectral = {}, {}
        if shared:
            dp, ds = self._convert_patch_d(tdis.discriminator)
            params["d_shared"] = dp
            if ds:
                spectral["d_shared"] = ds
        else:
            for i, td in enumerate(tdis.discriminators):
                dp, ds = self._convert_patch_d(td)
                params[f"d_{i}"] = dp
                if ds:
                    spectral[f"d_{i}"] = ds
        variables = _merge_variables(variables, params, spectral)
        want_out, want_feat, _ = tdis(nchw(x))
        (got_out, got_feat, _), _ = jdis.apply(
            variables, x, training=True, mutable=["spectral"])
        assert len(got_out) == len(want_out) == self.ND
        for scale, (g, w) in enumerate(zip(got_out, want_out)):
            np.testing.assert_allclose(
                np.asarray(g), to_nhwc(w), rtol=2e-3, atol=2e-4,
                err_msg=f"logits scale {scale}")
        for scale in range(self.ND):
            for g, w in zip(got_feat[scale], want_feat[scale]):
                np.testing.assert_allclose(
                    np.asarray(g), to_nhwc(w), rtol=2e-3, atol=2e-4,
                    err_msg=f"features scale {scale}")

"""Request-scoped serving observability (ISSUE 20): trace span
completeness/contiguity, deterministic sampling, eviction attribution,
SLO error-budget math + breach emission, chaos latency injection,
loadgen determinism, the burn-rate health gates, and the --serving
report section."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import __graft_entry__ as ge  # noqa: E402
from imaginaire_tpu import telemetry  # noqa: E402
from imaginaire_tpu.registry import resolve  # noqa: E402
from imaginaire_tpu.resilience import chaos as chaos_mod  # noqa: E402
from imaginaire_tpu.serving import (  # noqa: E402
    REQUEST_SPANS,
    ErrorBudget,
    RequestTrace,
    ServeRequest,
    ServingEngine,
    ServingError,
    Tracer,
    poisson_arrivals,
    run_open_loop,
    slo_settings,
)
from imaginaire_tpu.serving.engine import _percentile  # noqa: E402
from imaginaire_tpu.serving.tracing import sampled  # noqa: E402
from imaginaire_tpu.telemetry.report import (  # noqa: E402
    render_serving_report,
    summarize,
)
from scripts.check_run_health import check_health  # noqa: E402

H = W = 64
LABELS = 5


def _mem_telemetry():
    return telemetry.configure(enabled=True, sinks=[],
                               flush_every_n_steps=0, mfu=False)


def _mk_request(seed, h=H, w=W):
    rng = np.random.RandomState(seed)
    return ServeRequest(
        data={"label": rng.rand(1, h, w, LABELS).astype(np.float32),
              "images": np.zeros((1, h, w, 3), np.float32)},
        seed=seed)


def _events(tm, kind=None, name=None):
    with tm._lock:
        evs = list(tm._events)
    return [e for e in evs
            if (kind is None or e.get("kind") == kind)
            and (name is None or e.get("name") == name)]


# ----------------------------------------------------------- sampling


def test_sampling_deterministic_pure_function():
    assert all(sampled(i, 1.0) for i in range(50))
    assert not any(sampled(i, 0.0) for i in range(50))
    first = [sampled(i, 0.25) for i in range(2000)]
    assert first == [sampled(i, 0.25) for i in range(2000)]
    frac = sum(first) / len(first)
    assert 0.15 < frac < 0.35, frac


# ------------------------------------------------------- trace spans


def test_trace_spans_contiguous_and_sum_to_e2e():
    tr = RequestTrace("spade/r1", 1, t0=100.0)
    tr.begin("admit", t=100.0)
    t = 100.0
    for name in REQUEST_SPANS[1:]:
        t += 0.010
        tr.mark(name, t=t)
    tr.finish(t=t + 0.010)
    assert tr.span_names() == list(REQUEST_SPANS)
    span_sum = sum(s["dur_ms"] for s in tr.spans)
    assert span_sum == pytest.approx(tr.e2e_ms, rel=1e-6)
    assert tr.e2e_ms == pytest.approx(70.0, rel=1e-6)


def test_trace_dominant_span_and_annotations():
    tr = RequestTrace("spade/r2", 2, t0=0.0)
    tr.begin("admit", t=0.0)
    tr.mark("queue_wait", t=0.001)
    tr.mark("execute", t=0.002)
    tr.finish(t=0.042)  # execute ran 40ms
    name, dur = tr.dominant_span()
    assert name == "execute" and dur == pytest.approx(40.0, rel=1e-3)
    tr.annotate(executable="serve/spade/64x64/bs4", padded=2)
    rec = tr.record()
    assert rec["executable"] == "serve/spade/64x64/bs4"
    assert rec["padded"] == 2 and rec["trace_id"] == "spade/r2"


def test_breach_trace_emitted_despite_sampling_drop():
    tm = _mem_telemetry()
    tracer = Tracer("spade", sample_rate=0.0)
    tr = tracer.admit(7, t0=0.0)
    tr.mark("respond", t=0.001).finish(t=0.002)
    assert tracer.emit(tr) is False  # dropped: unsampled, no breach
    tr2 = tracer.admit(8, t0=0.0)
    tr2.mark("respond", t=0.001).finish(t=0.002)
    tr2.slo_breach = True
    assert tracer.emit(tr2) is True  # breaches ALWAYS emit
    recs = _events(tm, kind="trace", name="trace/request")
    assert len(recs) == 1 and recs[0]["request_id"] == 8
    assert tracer.dropped == 1 and tracer.emitted == 1


# ------------------------------------------------------- error budget


def test_error_budget_math():
    b = ErrorBudget(p99_ms=100.0, availability=0.9, window=10)
    for _ in range(9):
        assert b.observe(10.0) is False
    assert b.burn_rate() == 0.0 and b.budget_remaining_frac() == 1.0
    _mem_telemetry()
    assert b.observe(500.0) is True  # 1 bad / 10 => bad_frac 0.1
    assert b.burn_rate() == pytest.approx(1.0)  # == allowed 0.1
    assert b.budget_remaining_frac() == pytest.approx(0.0)
    assert b.breaches == 1
    b.reset()
    assert b.burn_rate() == 0.0 and b.breaches == 0


def test_error_budget_rejection_counts_as_availability_failure():
    _mem_telemetry()
    b = ErrorBudget(p99_ms=100.0, availability=0.999, window=16)
    assert b.observe_rejected() is True
    assert b.rejected == 1 and b.breaches == 1
    assert b.burn_rate() > 1.0  # 1/1 bad vs 0.001 allowed


def test_error_budget_disabled_never_breaches():
    b = ErrorBudget(p99_ms=None)
    assert not b.enabled
    assert b.observe(1e9) is False
    assert b.observe_rejected() is False
    assert b.burn_rate() == 0.0 and b.breaches == 0


def test_slo_settings_parse():
    s = slo_settings({"serving": {"slo": {"p99_ms": 250,
                                          "availability": 0.99,
                                          "window": 64}}})
    assert s == {"p99_ms": 250.0, "availability": 0.99, "window": 64}
    assert slo_settings({})["p99_ms"] is None  # disabled by default
    assert slo_settings(None)["window"] == 256


# -------------------------------------------------- percentile (sat 2)


def test_percentile_tiny_samples():
    assert _percentile([], 0.99) is None
    assert _percentile([42.0], 0.5) == 42.0
    assert _percentile([42.0], 0.99) == 42.0
    # two samples: linear interpolation, not nearest-rank collapse
    assert _percentile([10.0, 20.0], 0.5) == pytest.approx(15.0)
    assert _percentile([10.0, 20.0], 0.99) == pytest.approx(19.9)
    assert _percentile([10.0, 20.0, 30.0], 0.0) == 10.0
    assert _percentile([10.0, 20.0, 30.0], 1.0) == 30.0


# -------------------------------------------------- engine integration


@pytest.fixture(scope="module")
def traced_engine():
    """Tiny SPADE engine with tracing at 1.0 and the budget armed at a
    breach-proof objective (the span/attribution tests need traces, not
    breaches)."""
    _mem_telemetry()
    cfg = ge._tiny_cfg()
    cfg.serving.buckets = [[H, W], [96, 96]]
    cfg.serving.batch_sizes = [1, 4]
    cfg.serving.trace_sample_rate = 1.0
    cfg.serving.slo.p99_ms = 600000.0
    batch = ge._tiny_batch(1, h=H, w=W, labels=LABELS)
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    engine = ServingEngine(cfg, trainer=trainer)
    engine.register_example(trainer.start_of_iteration(batch, 0))
    engine.initialize(example_batch=batch)
    return engine


def test_padded_bucketed_request_trace_complete(traced_engine):
    """The acceptance shape: a padded, bucketed request's trace carries
    every pipeline span exactly once, monotone, summing to within
    tolerance of the wall e2e latency."""
    tm = _mem_telemetry()
    reqs = ([_mk_request(900 + i) for i in range(5)]  # 4+1 @64
            + [_mk_request(950 + i, h=96, w=96) for i in range(2)])
    traced_engine.serve(reqs)
    recs = {r["request_id"]: r
            for r in _events(tm, kind="trace", name="trace/request")}
    for req in reqs:
        rec = recs[req.id]
        names = [s["name"] for s in rec["spans"]]
        assert names == list(REQUEST_SPANS), names  # each exactly once
        durs = [s["dur_ms"] for s in rec["spans"]]
        assert all(d >= 0.0 for d in durs)  # contiguous => monotone
        assert sum(durs) == pytest.approx(rec["e2e_ms"], rel=0.10,
                                          abs=0.5)
        assert rec["executable"].startswith("serve/spade/")
        assert rec["warm_hit"] in (True, False)
    # the 2-request 96x96 group padded up to bs4
    padded = [recs[r.id] for r in reqs[5:]]
    assert all(p["padded"] == 2 and p["batch_size"] == 4
               for p in padded), padded
    # SLO counters flowed alongside (armed budget, no breaches)
    assert _events(tm, kind="counter", name="serve/slo/burn_rate")
    assert not _events(tm, kind="meta", name="serve/slo/breach")


def test_queue_depth_emitted_once_per_batch(traced_engine):
    """Satellite 1: serve/queue_depth comes from the post-batch flush
    block only — submit() must not interleave a second cadence."""
    tm = _mem_telemetry()
    for i in range(3):
        traced_engine.submit(_mk_request(1000 + i))
    assert not _events(tm, kind="counter", name="serve/queue_depth")
    traced_engine.flush()
    depth_events = _events(tm, kind="counter", name="serve/queue_depth")
    flush_events = _events(tm, kind="counter", name="serve/requests")
    assert len(depth_events) >= 1
    # exactly one emission per post-batch flush block, none at enqueue
    assert len(depth_events) == len(flush_events)


def test_evict_recompile_attribution():
    """A slow request caused by evict-then-recompile must say so: pool
    of ONE, alternate buckets, the re-admitted bucket's trace carries
    evict_recompile=True (a plain cold compile does not)."""
    tm = _mem_telemetry()
    cfg = ge._tiny_cfg()
    cfg.serving.buckets = [[H, W], [96, 96]]
    cfg.serving.batch_sizes = [1]
    cfg.serving.max_executables = 1
    cfg.serving.trace_sample_rate = 1.0
    batch = ge._tiny_batch(1, h=H, w=W, labels=LABELS)
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    engine = ServingEngine(cfg, trainer=trainer)
    engine.register_example(trainer.start_of_iteration(batch, 0))
    engine.initialize(example_batch=batch)
    r_cold = _mk_request(1100)
    r_evictor = _mk_request(1101, h=96, w=96)
    r_rebuilt = _mk_request(1102)
    engine.serve([r_cold])     # cold build @64
    engine.serve([r_evictor])  # evicts the 64 executable
    engine.serve([r_rebuilt])  # rebuild of a previously-evicted key
    recs = {r["request_id"]: r
            for r in _events(tm, kind="trace", name="trace/request")}
    assert recs[r_cold.id]["evict_recompile"] is False  # cold != evicted
    assert recs[r_rebuilt.id]["evict_recompile"] is True
    assert recs[r_rebuilt.id]["warm_hit"] is False


def test_queue_shed_request_trace_and_budget(traced_engine):
    tm = _mem_telemetry()
    traced_engine.settings["max_queue"] = 2
    traced_engine.queue.max_depth = 2
    rejected_before = traced_engine.budget.rejected
    try:
        with pytest.raises(ServingError):
            for i in range(4):
                traced_engine.submit(_mk_request(1200 + i))
    finally:
        traced_engine.flush()
        traced_engine.settings["max_queue"] = 64
        traced_engine.queue.max_depth = 64
    assert traced_engine.budget.rejected == rejected_before + 1
    breach = _events(tm, kind="meta", name="serve/slo/breach")
    assert breach and breach[-1]["rejected"] is True
    shed = [r for r in _events(tm, kind="trace", name="trace/request")
            if r.get("rejected")]
    assert shed and shed[-1]["slo_breach"] is True
    assert shed[-1]["spans"][-1]["name"] == "respond"


# ------------------------------------------------------- stream traces


class _StubV2VTrainer:
    num_frames_G = 3
    state = {"vars_G": {"params": {}}}
    net_G = None

    def inference_params(self):
        return {"params": {}}

    def _start_of_iteration(self, data, it):
        return data

    def _get_data_t(self, data, t, prev_labels, prev_images):
        return {"label": data["label"], "prev_labels": prev_labels,
                "prev_images": prev_images}

    def _apply_G(self, vars_G, data_t, rng, training=False):
        return {"fake_images": 2.0 * data_t["label"][..., :3]}, {}


def _frame(value):
    return {"label": np.full((1, H, W, 3), value, np.float32)}


def test_stream_traces_keep_per_stream_isolation():
    tm = _mem_telemetry()
    cfg = ge._tiny_cfg()
    cfg.serving.buckets = [[H, W]]
    cfg.serving.trace_sample_rate = 1.0
    engine = ServingEngine(cfg, trainer=_StubV2VTrainer(),
                           family="fs_vid2vid")
    a = engine.stream("camA")
    b = engine.stream("camB")
    a.step(_frame(1.0))
    b.step(_frame(1.0))
    a.step(_frame(1.0))
    a.reset()
    engine.close_stream("camA")
    life = _events(tm, kind="trace", name="trace/stream")
    by_event = {}
    for ev in life:
        by_event.setdefault(ev["event"], []).append(ev)
    assert {e["stream_id"] for e in by_event["open"]} == {"camA", "camB"}
    assert by_event["reset"][0]["stream_id"] == "camA"
    assert by_event["close"][0]["stream_id"] == "camA"
    frames = [r for r in _events(tm, kind="trace", name="trace/request")
              if r.get("stream_id")]
    per_stream = {}
    for r in frames:
        per_stream.setdefault(r["stream_id"], []).append(r["frame"])
    # frame numbering is per-stream (camA interleaved twice, camB once)
    assert per_stream == {"camA": [0, 1], "camB": [0]}
    assert all(r["trace_id"].startswith(f"fs_vid2vid/{r['stream_id']}/")
               for r in frames)


# ----------------------------------------------------------- chaos hook


def test_chaos_delay_serve_one_shot():
    tm = _mem_telemetry()
    chaos = chaos_mod.ChaosMonkey(chaos_mod.chaos_settings(
        {"chaos": {"enabled": True, "delay_serve_at_request": 2,
                   "delay_serve_ms": 1.0}}))
    chaos.maybe_delay_serve(1)  # before the armed ordinal: no-op
    chaos.maybe_delay_serve(2)
    chaos.maybe_delay_serve(2)  # one-shot: a retry never re-fires
    metas = _events(tm, kind="meta", name="chaos/delay_serve")
    assert len(metas) == 1 and metas[0]["step"] == 2
    assert chaos_mod.chaos_settings({})["delay_serve_at_request"] is None
    chaos_mod._NullChaos().maybe_delay_serve(2)  # inert default


# -------------------------------------------------------------- loadgen


def test_poisson_arrivals_deterministic_and_rate_shaped():
    a1 = poisson_arrivals(100.0, 5.0, np.random.default_rng(3))
    a2 = poisson_arrivals(100.0, 5.0, np.random.default_rng(3))
    assert a1 == a2
    assert all(0 < t < 5.0 for t in a1)
    assert a1 == sorted(a1)
    assert 350 < len(a1) < 650  # ~500 expected


def test_open_loop_point_shape(traced_engine):
    _mem_telemetry()
    traced_engine.reset_stats()
    rng = np.random.RandomState(5)
    lanes = {(H, W): {"label": rng.rand(1, H, W, LABELS)
                      .astype(np.float32),
                      "images": np.zeros((1, H, W, 3), np.float32)}}
    point = run_open_loop(traced_engine, rate_rps=40.0, duration_s=0.4,
                          lanes=lanes, seed=11)
    assert point["mode"] == "open" and point["offered_rps"] == 40.0
    assert point["served"] == point["requests"] > 0
    assert point["rejected"] == 0
    assert point["p50_ms"] > 0 and point["p99_ms"] >= point["p50_ms"]
    assert point["queue_depth_max"] >= 0
    assert point["slo_burn_rate"] == 0.0  # breach-proof objective


def test_reset_stats_clears_window_but_not_step_axis(traced_engine):
    _mem_telemetry()
    traced_engine.serve([_mk_request(1300)])
    batches_before = traced_engine.stats()["batches"]
    assert traced_engine.stats()["requests"] > 0
    traced_engine.reset_stats()
    st = traced_engine.stats()
    assert st["requests"] == 0 and st["p99_ms"] is None
    assert st["slo_burn_rate"] == 0.0 and st["slo_breaches"] == 0
    # the counter step axis stays monotone across measurement windows
    assert st["batches"] == batches_before


# ------------------------------------------------------------ SLO gates


def _summary(burn_max=0.0, budget_min=1.0, present=True):
    return {"serving": {
        "present": True, "p99_ms": 10.0, "queue_depth": 0,
        "slo": {"present": present, "burn_rate_max": burn_max,
                "budget_remaining_min": budget_min, "breaches": 2,
                "rejected": 1,
                "breach_events": [{"dominant_span": "execute"}]},
    }}


def test_burn_rate_gate_pass():
    assert check_health(_summary(burn_max=0.4),
                        max_slo_burn_rate=0.5) == []


def test_burn_rate_gate_fail_names_dominant_span():
    failures = check_health(_summary(burn_max=250.0),
                            max_slo_burn_rate=0.5)
    assert any("burn" in f and "execute" in f for f in failures), failures


def test_budget_floor_gate_fail():
    failures = check_health(_summary(budget_min=0.1),
                            min_slo_budget_frac=0.5)
    assert any("budget" in f for f in failures), failures


def test_slo_gates_graph_gated_without_slo_counters():
    assert check_health(_summary(burn_max=99.0, present=False),
                        max_slo_burn_rate=0.001,
                        min_slo_budget_frac=0.999) == []
    assert check_health({}, max_slo_burn_rate=0.001) == []


# --------------------------------------------------------------- report


def _synthetic_events():
    evs = [
        {"kind": "counter", "name": "serve/p99_ms", "value": 30.0,
         "step": 1, "t": 1.0},
        {"kind": "counter", "name": "serve/requests", "value": 2,
         "step": 1, "t": 1.0},
        {"kind": "counter", "name": "serve/slo/burn_rate", "value": 2.5,
         "step": 1, "t": 1.0},
        {"kind": "counter", "name": "serve/slo/budget_remaining_frac",
         "value": 0.0, "step": 1, "t": 1.0},
        {"kind": "meta", "name": "serve/slo/config", "p99_ms": 25.0,
         "availability": 0.999, "window": 256, "t": 1.0},
        {"kind": "meta", "name": "serve/slo/breach", "target_ms": 25.0,
         "rejected": False, "e2e_ms": 30.0, "trace_id": "spade/r1",
         "dominant_span": "execute", "dominant_span_ms": 28.0, "t": 1.0},
        {"kind": "trace", "name": "trace/request", "trace_id": "spade/r1",
         "request_id": 1, "trace_kind": "request", "sampled": True,
         "slo_breach": True, "e2e_ms": 30.0, "t": 1.0,
         "spans": [{"name": "admit", "dur_ms": 0.5},
                   {"name": "queue_wait", "dur_ms": 1.0},
                   {"name": "execute", "dur_ms": 28.0},
                   {"name": "respond", "dur_ms": 0.5}],
         "executable": "serve/spade/64x64/bs1", "warm_hit": True,
         "evict_recompile": False},
        {"kind": "trace", "name": "trace/stream", "event": "open",
         "stream_id": "camA", "family": "fs_vid2vid", "t": 1.0},
    ]
    return evs


def test_summarize_trace_and_slo_blocks():
    s = summarize(_synthetic_events())
    sv = s["serving"]
    tr = sv["traces"]
    assert tr["present"] and tr["count"] == 1 and tr["breaches"] == 1
    assert tr["spans"]["execute"]["total_ms"] == pytest.approx(28.0)
    assert tr["stream_ids"] == ["camA"]
    slo = sv["slo"]
    assert slo["present"] and slo["burn_rate_max"] == 2.5
    assert slo["budget_remaining_min"] == 0.0
    assert slo["config"]["p99_ms"] == 25.0
    assert slo["breach_events"][0]["dominant_span"] == "execute"


def test_render_serving_report():
    out = render_serving_report(_synthetic_events())
    assert "execute" in out and "spade/r1" in out
    assert "burn" in out.lower()
    assert "BREACH" in out


def test_render_serving_report_without_serving_events():
    out = render_serving_report([{"kind": "counter", "name": "x",
                                  "value": 1, "step": 0, "t": 0.0}])
    assert "no serving telemetry" in out.lower()

"""ISSUE-14 software-pipelined rollout dispatch (parallel/pipeline.py):
FrameDAG ordering + donation-safety units, deferred-completion depth
semantics, dispatch-gap/overlap meters, loop-invariant hoisting on the
virtual mesh, trainer eligibility gates, and (slow) pipelined-vs-
sequential bit parity over every state leaf with a zero-recompile
assert through the compile ledger."""

import os
import time

import jax
import numpy as np
import pytest

from imaginaire_tpu.config import AttrDict, Config
from imaginaire_tpu.parallel.pipeline import (
    STAGES,
    FrameDAG,
    PipelineOrderError,
    RolloutPipeline,
    hoist_invariants,
    pipeline_settings,
)
from imaginaire_tpu.registry import resolve

CFG = os.path.join(os.path.dirname(__file__), "..", "configs", "unit_test",
                   "vid2vid_street.yaml")


class TestFrameDAG:
    def test_legal_issue_order(self):
        dag = FrameDAG()
        for t in range(3):
            for stage in STAGES:
                dag.mark(stage, t)
        assert dag.frames == 3
        assert dag.done("grads", 2)
        # order() replays the marks as the canonical topological order
        assert dag.order() == [(s, t) for t in range(3) for s in STAGES]

    def test_deps_drop_preroll_frames(self):
        dag = FrameDAG()
        # frame 0 has no G_{-1} to wait on
        assert dag.deps("data", 0) == ()
        assert dag.deps("D", 0) == (("data", 0),)
        assert dag.deps("D", 1) == (("data", 1), ("G", 0))
        with pytest.raises(KeyError):
            dag.deps("warp", 0)

    def test_out_of_order_within_frame_raises(self):
        dag = FrameDAG()
        dag.mark("data", 0)
        with pytest.raises(PipelineOrderError):
            dag.mark("G", 0)  # D_0 never issued

    def test_donated_state_edge_across_frames(self):
        """D_t consumes the state handle G_{t-1} returns, and data_{t+1}
        consumes G_t's ring-buffer output: issuing either before G_t is a
        donation-safety violation and must raise, not silently reorder."""
        dag = FrameDAG()
        dag.mark("data", 0)
        dag.mark("D", 0)
        with pytest.raises(PipelineOrderError, match="donated state"):
            dag.mark("data", 1)  # G_0 hasn't produced the ring buffers

    def test_override_satisfies_downstream(self):
        """A _frame_override frame (wc-vid2vid) supplies frame t's output
        outside the DAG; satisfy() must unblock frame t+1."""
        dag = FrameDAG()
        dag.satisfy(0)
        dag.mark("data", 1)
        dag.mark("D", 1)
        assert dag.frames == 2


class TestRolloutPipeline:
    def test_depth_zero_drains_inline(self):
        pipe = RolloutPipeline(depth=0).begin()
        calls = []
        pipe.defer(lambda: calls.append(1))
        assert calls == [1]

    def test_depth_bounds_outstanding_records_fifo(self):
        pipe = RolloutPipeline(depth=2).begin()
        calls = []
        for i in range(3):
            pipe.defer(lambda i=i: calls.append(i))
        # the third append drains only the OLDEST record
        assert calls == [0]
        pipe.drain()
        assert calls == [0, 1, 2]

    def test_finish_drains_everything(self):
        pipe = RolloutPipeline(depth=4).begin()
        calls = []
        pipe.defer(lambda: calls.append("a"))
        pipe.defer(lambda: calls.append("b"))
        summary = pipe.finish()
        assert calls == ["a", "b"]
        assert summary["depth"] == 4

    def test_begin_resets_meters_between_rollouts(self):
        pipe = RolloutPipeline(depth=1).begin()
        with pipe.frame(0):
            pipe.mark("data", 0)
        pipe.finish()
        pipe.begin()
        assert pipe.summary()["frames"] == 0

    def test_meters_dispatch_gap_and_overlap(self):
        """Two frame windows with a deliberate host stall between them:
        the stall lands in the dispatch gap, the overlap ratio drops
        below 1, and the frame count comes from the DAG (not the window
        count, which differs on the two-window sequential path)."""
        pipe = RolloutPipeline(depth=2).begin()
        for t in range(2):
            with pipe.frame(t):
                for stage in STAGES:
                    pipe.mark(stage, t)
                time.sleep(0.01)  # issue work
            time.sleep(0.02)  # host stall outside the window -> gap
        s = pipe.finish()
        assert s["frames"] == 2
        assert s["dispatch_gap_ms"] > 1.0
        assert s["issue_ms"] > 1.0
        assert 0.0 <= s["overlap_ratio"] < 1.0

    def test_negative_depth_clamps(self):
        assert RolloutPipeline(depth=-3).depth == 0


class TestPipelineSettings:
    def test_defaults(self):
        s = pipeline_settings(AttrDict())
        assert s == {"enabled": True, "depth": 2,
                     "overlap_collectives": True}

    def test_config_group_round_trip(self):
        cfg = Config(CFG)
        cfg.trainer.pipeline = AttrDict(
            enabled=False, depth=5, overlap_collectives=False)
        s = pipeline_settings(cfg)
        assert s == {"enabled": False, "depth": 5,
                     "overlap_collectives": False}

    def test_depth_clamped_non_negative(self):
        cfg = AttrDict(trainer=AttrDict(pipeline=AttrDict(depth=-1)))
        assert pipeline_settings(cfg)["depth"] == 0


class TestHoistInvariants:
    def test_no_constants_is_noop(self):
        data = {"x": np.ones(3)}
        out, nbytes = hoist_invariants(data, {})
        assert out is data and nbytes == 0

    def test_trivial_mesh_is_noop(self):
        from imaginaire_tpu.parallel.mesh import create_mesh

        mesh = create_mesh(("data",), (1,), devices=jax.devices()[:1])
        data = {"x": np.ones(3, np.float32)}
        out, nbytes = hoist_invariants(data, dict(data), mesh=mesh)
        assert nbytes == 0

    def test_sharded_operand_gathers_once_to_replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec

        from imaginaire_tpu.parallel.mesh import create_mesh

        mesh = create_mesh(("data",))
        sharded = jax.device_put(
            np.arange(32, dtype=np.float32).reshape(8, 4),
            NamedSharding(mesh, PartitionSpec("data")))
        data = {"ref": sharded, "skip": None}
        out, nbytes = hoist_invariants(
            data, {"ref": sharded, "skip": None}, mesh=mesh)
        assert nbytes == sharded.nbytes
        replicated = NamedSharding(mesh, PartitionSpec())
        assert out["ref"].sharding.is_equivalent_to(replicated, 2)
        np.testing.assert_array_equal(
            np.asarray(out["ref"]), np.asarray(sharded))
        # second hoist sees the replicated operand and gathers nothing
        out, nbytes = hoist_invariants(out, {"ref": out["ref"]}, mesh=mesh)
        assert nbytes == 0


def _build_trainer(tmp_path, tag, **trainer_overrides):
    cfg = Config(CFG)
    cfg.logdir = str(tmp_path / tag)
    # shrink the perceptual graph: equivalence, not capacity
    cfg.trainer.perceptual_loss.layers = ["relu_1_1", "relu_2_1"]
    cfg.trainer.perceptual_loss.weights = [0.5, 1.0]
    for key, value in trainer_overrides.items():
        setattr(cfg.trainer, key, value)
    return resolve(cfg.trainer.type, "Trainer")(cfg)


class TestEligibility:
    def test_vid2vid_default_is_eligible(self, tmp_path):
        trainer = _build_trainer(tmp_path, "elig")
        assert trainer._pipeline_eligible({}, 3)

    def test_knob_off_or_depth_zero_refuses(self, tmp_path):
        trainer = _build_trainer(
            tmp_path, "off", pipeline=AttrDict(enabled=False))
        assert not trainer._pipeline_eligible({}, 3)
        trainer = _build_trainer(
            tmp_path, "d0", pipeline=AttrDict(depth=0))
        assert not trainer._pipeline_eligible({}, 3)

    def test_rollback_policy_refuses(self, tmp_path):
        """rollback snapshots state per observation; deferring the
        observation past later frames' mutations would snapshot the
        wrong state, so the pipeline must stand down."""
        trainer = _build_trainer(tmp_path, "rb")
        trainer.diag.on_nonfinite = "rollback"
        assert not trainer._pipeline_eligible({}, 3)

    def test_wc_vid2vid_never_pipelines(self):
        from imaginaire_tpu.trainers import wc_vid2vid

        assert wc_vid2vid.Trainer._pipeline_eligible(object(), {}, 3) \
            is False


@pytest.mark.slow
class TestPipelinedParity:
    """The acceptance bar: the pipelined rollout is bit-identical to the
    sequential loop in fp32 — losses, params, optimizer and EMA state,
    every leaf — because only host poll TIMING changes; programs, inputs
    and observation order do not."""

    def _run(self, tmp_path, tag, pipeline, iters=2):
        from tests.test_vid2vid import video_batch

        trainer = _build_trainer(
            tmp_path, tag,
            pipeline=AttrDict(**pipeline),
            model_average=True,
            model_average_start_iteration=0,
            model_average_beta=0.5,
        )
        data = video_batch(np.random.RandomState(7), t=4)
        trainer.init_state(jax.random.PRNGKey(0), data)
        losses = None
        for it in range(1, iters + 1):
            batch = trainer.start_of_iteration(dict(data), it)
            losses = trainer.gen_update(batch)
        return ({k: float(jax.device_get(v)) for k, v in losses.items()},
                jax.device_get(trainer.state))

    def test_bit_parity_and_zero_recompiles(self, tmp_path):
        from imaginaire_tpu.telemetry import xla_obs

        losses_seq, state_seq = self._run(
            tmp_path, "seq", {"enabled": False})
        losses_pipe, state_pipe = self._run(
            tmp_path, "pipe",
            {"enabled": True, "depth": 2, "overlap_collectives": True})
        assert set(losses_seq) == set(losses_pipe)
        for k in losses_seq:
            assert losses_pipe[k] == losses_seq[k], (
                f"loss {k!r}: pipelined {losses_pipe[k]!r} != "
                f"sequential {losses_seq[k]!r}")
        leaves_seq, tree_seq = jax.tree_util.tree_flatten(state_seq)
        leaves_pipe, tree_pipe = jax.tree_util.tree_flatten(state_pipe)
        assert tree_seq == tree_pipe
        assert len(leaves_seq) > 0
        for i, (a, b) in enumerate(zip(leaves_seq, leaves_pipe)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                f"state leaf {i} diverged under the pipelined dispatch")
        # EMA coverage: model_average=True put an ema_G collection in
        # the compared state
        assert "ema_G" in state_seq

        # zero post-warmup recompiles through the compile ledger: the
        # ring-buffer growth recompiles all land inside iteration 1's
        # gen_update; a fresh trainer run two iterations deep is in
        # steady state, and one more pipelined rollout must not add a
        # single compile or recompile
        trainer = _build_trainer(
            tmp_path, "ledger",
            pipeline=AttrDict(enabled=True, depth=2),
        )
        from tests.test_vid2vid import video_batch

        data = video_batch(np.random.RandomState(7), t=4)
        trainer.init_state(jax.random.PRNGKey(0), data)
        for it in range(1, 3):
            batch = trainer.start_of_iteration(dict(data), it)
            trainer.gen_update(batch)
        mark = xla_obs.ledger().snapshot()
        batch = trainer.start_of_iteration(dict(data), 3)
        trainer.gen_update(batch)
        steady = xla_obs.snapshot_delta(mark)
        assert steady["recompiles"] == 0, steady
        assert steady["compiles"] == 0, steady

"""UNIT/MUNIT family: dataset sampling, 2-iteration training smokes,
inference paths (mirrors the reference's 2-iter unit-test strategy,
SURVEY.md §4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu.config import Config
from imaginaire_tpu.registry import resolve

HERE = os.path.dirname(__file__)
CFG_MUNIT = os.path.join(HERE, "..", "configs", "unit_test", "munit.yaml")
CFG_UNIT = os.path.join(HERE, "..", "configs", "unit_test", "unit.yaml")


def unpaired_batch(rng, h=64, w=64):
    return {
        "images_a": jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32)) * 2 - 1,
        "images_b": jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32)) * 2 - 1,
    }


class TestUnpairedDataset:
    def test_independent_pools_and_shapes(self):
        cfg = Config(CFG_MUNIT)
        ds_cls = resolve(cfg.data.type, "Dataset")
        ds = ds_cls(cfg)
        assert len(ds.items["images_a"]) == 3
        assert len(ds.items["images_b"]) == 2
        assert len(ds) == 3  # max of pools
        item = ds[0]
        assert item["images_a"].shape == (64, 64, 3)
        assert item["images_b"].shape == (64, 64, 3)
        assert item["images_a"].min() >= -1.0 and item["images_a"].max() <= 1.0

    def test_inference_modulo_indexing(self):
        cfg = Config(CFG_MUNIT)
        ds = resolve(cfg.data.type, "Dataset")(cfg, is_inference=True)
        # index 2 maps to images_b pool index 2 % 2 == 0 without error
        item = ds[2]
        assert item["images_b"].shape == (64, 64, 3)


@pytest.mark.slow
class TestUnpairedTraining:
    @pytest.mark.parametrize("cfg_path,expected_losses", [
        (CFG_MUNIT, {"gan", "image_recon", "style_recon", "content_recon",
                     "kl", "cycle_recon", "total"}),
        (CFG_UNIT, {"gan", "image_recon", "cycle_recon", "total"}),
    ])
    def test_two_iterations(self, rng, tmp_path, cfg_path, expected_losses):
        cfg = Config(cfg_path)
        cfg.logdir = str(tmp_path)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        trainer.init_state(jax.random.PRNGKey(0), unpaired_batch(rng))
        trainer.start_of_epoch(0)
        for it in range(1, 3):
            batch = trainer.start_of_iteration(unpaired_batch(rng), it)
            d = trainer.dis_update(batch)
            g = trainer.gen_update(batch)
            trainer.end_of_iteration(batch, 0, it)
        for name, v in {**d, **g}.items():
            assert np.isfinite(float(jax.device_get(v))), name
        assert expected_losses <= set(g.keys())

    def test_munit_gp_and_consistency(self, rng, tmp_path):
        cfg = Config(CFG_MUNIT)
        cfg.logdir = str(tmp_path)
        cfg.trainer.loss_weight.gp = 1.0
        cfg.trainer.loss_weight.consistency_reg = 1.0
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        trainer.init_state(jax.random.PRNGKey(0), unpaired_batch(rng))
        batch = trainer.start_of_iteration(unpaired_batch(rng), 1)
        d = trainer.dis_update(batch)
        assert "gp" in d and "consistency_reg" in d
        for name, v in d.items():
            assert np.isfinite(float(jax.device_get(v))), name

    def test_munit_inference_both_directions(self, rng, tmp_path):
        cfg = Config(CFG_MUNIT)
        cfg.logdir = str(tmp_path)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = unpaired_batch(rng)
        trainer.init_state(jax.random.PRNGKey(0), data)
        variables = trainer.inference_params()
        for a2b in (True, False):
            for random_style in (True, False):
                out = trainer.net_G.apply(
                    variables, data, a2b=a2b, random_style=random_style,
                    rngs={"noise": jax.random.PRNGKey(1)},
                    method=trainer.net_G.inference)
                assert out.shape == (1, 64, 64, 3)

    def test_unit_inference(self, rng, tmp_path):
        cfg = Config(CFG_UNIT)
        cfg.logdir = str(tmp_path)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = unpaired_batch(rng)
        trainer.init_state(jax.random.PRNGKey(0), data)
        out = trainer.net_G.apply(
            trainer.inference_params(), data, a2b=True,
            rngs={"noise": jax.random.PRNGKey(1)},
            method=trainer.net_G.inference)
        assert out.shape == (1, 64, 64, 3)

    def test_munit_random_styles_differ(self, rng, tmp_path):
        """Random style sampling must vary with the noise rng."""
        cfg = Config(CFG_MUNIT)
        cfg.logdir = str(tmp_path)
        trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
        data = unpaired_batch(rng)
        trainer.init_state(jax.random.PRNGKey(0), data)
        variables = trainer.inference_params()
        outs = []
        for seed in (1, 2):
            out = trainer.net_G.apply(
                variables, data, a2b=True, random_style=True,
                rngs={"noise": jax.random.PRNGKey(seed)},
                method=trainer.net_G.inference)
            outs.append(np.asarray(out))
        assert not np.allclose(outs[0], outs[1])

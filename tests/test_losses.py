"""Loss numerics vs torch-derived golden values (ref semantics in
imaginaire/losses/: gan.py, feature_matching.py, kl.py, perceptual.py,
flow.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from imaginaire_tpu.losses import (
    FlowLoss,
    PerceptualLoss,
    feature_matching_loss,
    gan_loss,
    gaussian_kl_loss,
    masked_l1_loss,
)


@pytest.fixture
def logits(rng):
    return rng.randn(2, 8, 8, 1).astype(np.float32)


class TestGANLoss:
    def test_hinge_dis_real(self, logits):
        got = gan_loss(jnp.asarray(logits), True, "hinge", dis_update=True)
        t = torch.from_numpy(logits)
        want = -torch.mean(torch.min(t - 1, t * 0))
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-6)

    def test_hinge_dis_fake(self, logits):
        got = gan_loss(jnp.asarray(logits), False, "hinge", dis_update=True)
        t = torch.from_numpy(logits)
        want = -torch.mean(torch.min(-t - 1, t * 0))
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-6)

    def test_hinge_gen(self, logits):
        got = gan_loss(jnp.asarray(logits), True, "hinge", dis_update=False)
        np.testing.assert_allclose(got, -logits.mean(), rtol=1e-6)

    def test_non_saturated(self, logits):
        got = gan_loss(jnp.asarray(logits), True, "non_saturated", dis_update=True)
        t = torch.from_numpy(logits)
        want = F.binary_cross_entropy_with_logits(t, torch.ones_like(t))
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-5)

    def test_least_square(self, logits):
        got = gan_loss(jnp.asarray(logits), False, "least_square", dis_update=True)
        t = torch.from_numpy(logits)
        want = 0.5 * F.mse_loss(t, torch.zeros_like(t))
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-6)

    def test_wasserstein(self, logits):
        got = gan_loss(jnp.asarray(logits), False, "wasserstein")
        np.testing.assert_allclose(got, logits.mean(), rtol=1e-6)

    def test_multiscale_averages_scales(self, rng):
        outs = [rng.randn(2, s, s, 1).astype(np.float32) for s in (8, 4)]
        got = gan_loss([jnp.asarray(o) for o in outs], True, "hinge", dis_update=False)
        want = np.mean([-o.mean() for o in outs])
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_gen_update_requires_real_target(self, logits):
        with pytest.raises(ValueError):
            gan_loss(jnp.asarray(logits), False, "hinge", dis_update=False)


class TestFeatureMatching:
    def test_matches_torch(self, rng):
        fake = [[rng.randn(2, 4, 4, 8).astype(np.float32) for _ in range(3)]
                for _ in range(2)]
        real = [[rng.randn(2, 4, 4, 8).astype(np.float32) for _ in range(3)]
                for _ in range(2)]
        got = feature_matching_loss(
            jax.tree_util.tree_map(jnp.asarray, fake),
            jax.tree_util.tree_map(jnp.asarray, real))
        want = 0.0
        for i in range(2):
            for j in range(3):
                want += 0.5 * np.abs(fake[i][j] - real[i][j]).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_real_branch_stops_gradient(self, rng):
        f = jnp.asarray(rng.randn(1, 2, 2, 2).astype(np.float32))
        r = jnp.asarray(rng.randn(1, 2, 2, 2).astype(np.float32))
        g = jax.grad(lambda rr: feature_matching_loss([[f]], [[rr]]))(r)
        assert np.all(np.asarray(g) == 0)


def test_gaussian_kl(rng):
    mu = rng.randn(4, 16).astype(np.float32)
    logvar = rng.randn(4, 16).astype(np.float32)
    got = gaussian_kl_loss(jnp.asarray(mu), jnp.asarray(logvar))
    tm, tl = torch.from_numpy(mu), torch.from_numpy(logvar)
    want = -0.5 * torch.sum(1 + tl - tm.pow(2) - tl.exp())
    np.testing.assert_allclose(got, want.numpy(), rtol=1e-4)
    # logvar=None → standard normal posterior variance.
    got0 = gaussian_kl_loss(jnp.asarray(mu))
    np.testing.assert_allclose(got0, 0.5 * np.sum(mu ** 2), rtol=1e-4)


class TestMaskedL1:
    def test_matches_torch(self, rng):
        x = rng.randn(2, 4, 4, 3).astype(np.float32)
        t = rng.randn(2, 4, 4, 3).astype(np.float32)
        m = (rng.rand(2, 4, 4, 1) > 0.5).astype(np.float32)
        got = masked_l1_loss(jnp.asarray(x), jnp.asarray(t), jnp.asarray(m))
        tm = torch.from_numpy(np.broadcast_to(m, x.shape).copy())
        want = F.l1_loss(torch.from_numpy(x) * tm, torch.from_numpy(t) * tm)
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-5)

    def test_normalize_over_valid(self, rng):
        x = rng.randn(2, 4, 4, 3).astype(np.float32)
        m = np.zeros((2, 4, 4, 1), np.float32)
        m[:, :2] = 1.0
        got = masked_l1_loss(jnp.asarray(x), jnp.zeros_like(x), jnp.asarray(m),
                             normalize_over_valid=True)
        base = np.abs(x * np.broadcast_to(m, x.shape)).mean()
        want = base * x.size / (m.sum() * 3 + 1e-6)
        np.testing.assert_allclose(got, want, rtol=1e-4)


class TestPerceptual:
    def test_vgg19_layers_and_loss(self, key, rng):
        ploss = PerceptualLoss(
            network="vgg19",
            layers=["relu_1_1", "relu_2_1", "relu_3_1", "relu_4_1", "relu_5_1"],
            weights=[0.03125, 0.0625, 0.125, 0.25, 1.0],
            compute_dtype=jnp.float32, allow_random_init=True)
        params = ploss.init_params(key, image_hw=(64, 64))
        a = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32)) * 2 - 1
        b = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32)) * 2 - 1
        loss = ploss(params, a, b)
        assert np.isfinite(loss) and loss > 0
        np.testing.assert_allclose(ploss(params, a, a), 0.0, atol=1e-5)

    def test_feature_shapes(self, key, rng):
        ploss = PerceptualLoss(network="vgg19", layers=["relu_4_1"],
                               compute_dtype=jnp.float32, allow_random_init=True)
        params = ploss.init_params(key, image_hw=(64, 64))
        x = jnp.zeros((1, 64, 64, 3))
        feats = ploss.module.apply({"params": params}, x)
        # relu_4_1: 3 pools deep → 64/8 = 8 spatial, 512 channels.
        assert feats["relu_4_1"].shape == (1, 8, 8, 512)

    def test_gradient_flows_to_input(self, key, rng):
        ploss = PerceptualLoss(network="alexnet", layers=["relu_2"],
                               compute_dtype=jnp.float32, allow_random_init=True)
        params = ploss.init_params(key, image_hw=(64, 64))
        a = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32))
        b = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32))
        g = jax.grad(lambda x: ploss(params, x, b))(a)
        assert np.abs(np.asarray(g)).sum() > 0

    def test_num_scales(self, key, rng):
        ploss = PerceptualLoss(network="vgg16", layers=["relu_2_1"],
                               num_scales=2, compute_dtype=jnp.float32,
                               allow_random_init=True)
        params = ploss.init_params(key, image_hw=(64, 64))
        a = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32))
        b = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32))
        assert np.isfinite(ploss(params, a, b))


class TestFlowLoss:
    def test_full_terms(self, rng):
        h = w = 8

        def fake_flow_net(a, b):
            return (jnp.ones(a.shape[:3] + (2,)) * 0.5,
                    jnp.ones(a.shape[:3] + (1,)))

        floss = FlowLoss(fake_flow_net)
        data = {
            "image": jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32)),
            "real_prev_image": jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32)),
        }
        out = {
            "fake_images": jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32)),
            "warped_images": jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32)),
            "fake_flow_maps": jnp.zeros((1, h, w, 2)),
            "fake_occlusion_masks": jnp.full((1, h, w, 1), 0.5),
        }
        l_flow, l_warp, l_mask = floss(data, out)
        # flow L1 vs GT 0.5 everywhere → 0.5.
        np.testing.assert_allclose(l_flow, 0.5, rtol=1e-5)
        want_warp = np.abs(np.asarray(out["warped_images"]) -
                           np.asarray(data["image"])).mean()
        np.testing.assert_allclose(l_warp, want_warp, rtol=1e-5)
        assert np.isfinite(l_mask) and l_mask > 0


class TestPerceptualBackbones:
    def test_all_networks_compute(self, rng):
        """Every reference perceptual backbone (perceptual.py:175-358) has
        a port that initializes and yields a finite loss."""
        import jax

        from imaginaire_tpu.losses.perceptual import PerceptualLoss

        cases = {
            "vgg19": ["relu_1_1", "relu_4_1"],
            "vgg16": ["relu_3_1"],
            "vgg_face_dag": ["fc6", "relu_7"],
            "alexnet": ["relu_3"],
            "inception_v3": ["pool_2"],
            "resnet50": ["layer_2"],
            "robust_resnet50": ["layer_1"],
        }
        a = jnp.asarray(rng.rand(1, 96, 96, 3).astype(np.float32))
        b = jnp.asarray(rng.rand(1, 96, 96, 3).astype(np.float32))
        for net, layers in cases.items():
            p = PerceptualLoss(network=net, layers=layers,
                               allow_random_init=True)
            params = p.init_params(jax.random.PRNGKey(0), image_hw=(96, 96))
            loss = p(params, a, b)
            assert np.isfinite(float(loss)), net

    def test_resnet50_loader_roundtrip(self, rng, tmp_path):
        """Synthesized torchvision-style state dict loads into the exact
        param tree the Flax resnet expects."""
        import jax

        from imaginaire_tpu.losses.perceptual import (
            ResNet50Features,
            load_torch_resnet50_weights,
        )

        module = ResNet50Features(capture=("layer_1", "layer_4"))
        ref = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))

        flat = {}
        flat["conv1.weight"] = rng.rand(64, 3, 7, 7).astype(np.float32)
        for stat, init in (("weight", 1.0), ("bias", 0.0),
                           ("running_mean", 0.0), ("running_var", 1.0)):
            flat[f"bn1.{stat}"] = np.full((64,), init, np.float32)
        for li, (blocks, feats) in enumerate([(3, 64), (4, 128), (6, 256),
                                              (3, 512)], start=1):
            for bi in range(blocks):
                # tree-structure check only; in-channels are fabricated
                for ci, (o, i_, k) in enumerate(
                        [(feats, None, 1), (feats, feats, 3),
                         (feats * 4, feats, 1)], start=1):
                    w = rng.rand(o, 8, k, k).astype(np.float32)
                    flat[f"layer{li}.{bi}.conv{ci}.weight"] = w
                    for stat, init in (("weight", 1.0), ("bias", 0.0),
                                       ("running_mean", 0.0),
                                       ("running_var", 1.0)):
                        flat[f"layer{li}.{bi}.bn{ci}.{stat}"] = np.full(
                            (o,), init, np.float32)
                if bi == 0:
                    flat[f"layer{li}.{bi}.downsample.0.weight"] = rng.rand(
                        feats * 4, 8, 1, 1).astype(np.float32)
                    for stat, init in (("weight", 1.0), ("bias", 0.0),
                                       ("running_mean", 0.0),
                                       ("running_var", 1.0)):
                        flat[f"layer{li}.{bi}.downsample.1.{stat}"] = np.full(
                            (feats * 4,), init, np.float32)
        path = tmp_path / "resnet50.npz"
        np.savez(path, **flat)
        loaded = load_torch_resnet50_weights(str(path))
        # same tree structure (module names + leaf names)
        ref_keys = jax.tree_util.tree_structure(ref["params"])
        loaded_keys = jax.tree_util.tree_structure(loaded)
        assert ref_keys == loaded_keys


class TestVGGGoldenVsTorch:
    def test_vgg19_features_match_torch(self, rng, tmp_path):
        """Numerical golden test: the torchvision-layout VGG19 feature
        stack (built in torch with random weights), dumped in state-dict
        form and loaded through load_torch_vgg_weights, produces the
        same activations as our Flax VGGFeatures on the same input
        (ref: perceptual.py:175-208 semantics)."""
        import torch
        import torch.nn as tnn

        from imaginaire_tpu.losses.perceptual import (
            _VGG19_CFG,
            VGGFeatures,
            load_torch_vgg_weights,
        )

        layers, in_ch = [], 3
        for v in _VGG19_CFG:
            if v == "M":
                layers.append(tnn.MaxPool2d(2, 2))
            else:
                layers.append(tnn.Conv2d(in_ch, v, 3, padding=1))
                layers.append(tnn.ReLU(inplace=False))
                in_ch = v
        torch.manual_seed(0)
        features = tnn.Sequential(*layers).eval()

        npz = {f"features.{i}.{p}": t.detach().numpy()
               for i, m in enumerate(features)
               if isinstance(m, tnn.Conv2d)
               for p, t in (("weight", m.weight), ("bias", m.bias))}
        path = str(tmp_path / "vgg19.npz")
        np.savez(path, **npz)

        capture = ("relu_1_1", "relu_2_1", "relu_3_1", "relu_4_1",
                   "relu_5_1")
        params = load_torch_vgg_weights(path, "vgg19")
        module = VGGFeatures(capture=capture)

        x = rng.rand(2, 64, 64, 3).astype(np.float32)
        ours = module.apply({"params": params}, jnp.asarray(x))
        with torch.no_grad():
            t = torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))
            idx_of = {}
            block, bidx = 1, 1
            for i, m in enumerate(features):
                if isinstance(m, tnn.MaxPool2d):
                    block += 1
                    bidx = 1
                elif isinstance(m, tnn.ReLU):
                    idx_of[f"relu_{block}_{bidx}"] = i
                    bidx += 1
            acts = {}
            h = t
            for i, m in enumerate(features):
                h = m(h)
                for name, j in idx_of.items():
                    if j == i and name in capture:
                        acts[name] = h.numpy()
        for name in capture:
            theirs = np.transpose(acts[name], (0, 2, 3, 1))
            np.testing.assert_allclose(np.asarray(ours[name]), theirs,
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=name)

    def test_vgg16_features_match_torch(self, rng, tmp_path):
        """Same golden check for the VGG16 configuration."""
        import torch
        import torch.nn as tnn

        from imaginaire_tpu.losses.perceptual import (
            VGGFeatures,
            _VGG16_CFG,
            load_torch_vgg_weights,
        )

        layers, in_ch = [], 3
        for v in _VGG16_CFG:
            if v == "M":
                layers.append(tnn.MaxPool2d(2, 2))
            else:
                layers.append(tnn.Conv2d(in_ch, v, 3, padding=1))
                layers.append(tnn.ReLU(inplace=False))
                in_ch = v
        torch.manual_seed(1)
        features = tnn.Sequential(*layers).eval()
        npz = {f"features.{i}.{p}": t.detach().numpy()
               for i, m in enumerate(features)
               if isinstance(m, tnn.Conv2d)
               for p, t in (("weight", m.weight), ("bias", m.bias))}
        path = str(tmp_path / "vgg16.npz")
        np.savez(path, **npz)
        params = load_torch_vgg_weights(path, "vgg16")
        module = VGGFeatures(cfg=_VGG16_CFG, capture=("relu_3_1",))
        x = rng.rand(1, 64, 64, 3).astype(np.float32)
        ours = module.apply({"params": params}, jnp.asarray(x))
        with torch.no_grad():
            h = torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))
            # relu_3_1 = first conv+relu of block 3 -> Sequential idx 11
            for m in features[:12]:
                h = m(h)
        np.testing.assert_allclose(
            np.asarray(ours["relu_3_1"]),
            np.transpose(h.numpy(), (0, 2, 3, 1)), rtol=2e-4, atol=2e-5)

    def test_alexnet_features_match_torch(self, rng, tmp_path):
        """Golden check for the AlexNet port (torchvision Sequential
        layout: convs at 0,3,6,8,10)."""
        import torch
        import torch.nn as tnn

        from imaginaire_tpu.losses.perceptual import (
            AlexNetFeatures,
            load_torch_alexnet_weights,
        )

        torch.manual_seed(2)
        features = tnn.Sequential(
            tnn.Conv2d(3, 64, 11, stride=4, padding=2), tnn.ReLU(),
            tnn.MaxPool2d(3, 2),
            tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(),
            tnn.MaxPool2d(3, 2),
            tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(),
            tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(),
            tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(),
        ).eval()
        npz = {f"features.{i}.{p}": t.detach().numpy()
               for i, m in enumerate(features)
               if isinstance(m, tnn.Conv2d)
               for p, t in (("weight", m.weight), ("bias", m.bias))}
        path = str(tmp_path / "alexnet.npz")
        np.savez(path, **npz)
        params = load_torch_alexnet_weights(path)
        module = AlexNetFeatures(capture=("relu_5",))
        x = rng.rand(1, 96, 96, 3).astype(np.float32)
        ours = module.apply({"params": params}, jnp.asarray(x))
        with torch.no_grad():
            h = torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))
            h = features(h)
        np.testing.assert_allclose(
            np.asarray(ours["relu_5"]),
            np.transpose(h.numpy(), (0, 2, 3, 1)), rtol=2e-4, atol=2e-5)

"""Golden numerics for the native ops vs. independent numpy references.

The numpy references below re-derive the CUDA semantics documented in
SURVEY.md section 2.9 independently of the jnp implementations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu.ops import channelnorm, correlation, resample2d


def np_resample2d(x, flow):
    b, h, w, c = x.shape
    out = np.zeros_like(x)
    for bi in range(b):
        for i in range(h):
            for j in range(w):
                xf = j + flow[bi, i, j, 0]
                yf = i + flow[bi, i, j, 1]
                x0, y0 = np.floor(xf), np.floor(yf)
                ax, ay = xf - x0, yf - y0
                x0i = int(np.clip(x0, 0, w - 1))
                x1i = int(np.clip(x0 + 1, 0, w - 1))
                y0i = int(np.clip(y0, 0, h - 1))
                y1i = int(np.clip(y0 + 1, 0, h - 1))
                out[bi, i, j] = (
                    (1 - ay) * (1 - ax) * x[bi, y0i, x0i]
                    + (1 - ay) * ax * x[bi, y0i, x1i]
                    + ay * (1 - ax) * x[bi, y1i, x0i]
                    + ay * ax * x[bi, y1i, x1i]
                )
    return out


def np_correlation(x1, x2, pad, md, s2):
    b, h, w, c = x1.shape
    x2p = np.pad(x2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    steps = list(range(-md, md + 1, s2))
    out = np.zeros((b, h, w, len(steps) ** 2), np.float32)
    d = 0
    for dy in steps:
        for dx in steps:
            shifted = x2p[:, pad + dy : pad + dy + h, pad + dx : pad + dx + w, :]
            out[..., d] = (x1 * shifted).sum(-1) / c
            d += 1
    return out


@pytest.mark.parametrize("impl", ["jnp", "pallas_interpret"])
def test_resample2d_matches_reference(rng, impl):
    x = rng.randn(2, 5, 6, 3).astype(np.float32)
    flow = (rng.randn(2, 5, 6, 2) * 2).astype(np.float32)
    got = np.asarray(resample2d(jnp.asarray(x), jnp.asarray(flow), implementation=impl))
    want = np_resample2d(x, flow)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_resample2d_identity_flow(rng):
    x = rng.randn(1, 4, 4, 2).astype(np.float32)
    flow = np.zeros((1, 4, 4, 2), np.float32)
    got = np.asarray(resample2d(jnp.asarray(x), jnp.asarray(flow), implementation="jnp"))
    np.testing.assert_allclose(got, x, rtol=1e-6)


def test_resample2d_grad_is_scatter_add(rng):
    # d/dx of a warp that maps two output pixels onto one input pixel must
    # accumulate both contributions (the CUDA atomicAdd semantics,
    # resample2d_kernel.cu:122-125).
    x = jnp.ones((1, 1, 3, 1), jnp.float32)
    flow = jnp.zeros((1, 1, 3, 2), jnp.float32).at[0, 0, 1, 0].set(-1.0)  # pixel 1 reads pixel 0
    g = jax.grad(lambda x_: resample2d(x_, flow, implementation="jnp").sum())(x)
    np.testing.assert_allclose(np.asarray(g)[0, 0, :, 0], [2.0, 0.0, 1.0])


def test_resample2d_pallas_vjp_matches_jnp(rng):
    x = jnp.asarray(rng.randn(1, 4, 5, 2).astype(np.float32))
    flow = jnp.asarray((rng.randn(1, 4, 5, 2) * 1.5).astype(np.float32))
    g1 = jax.grad(lambda a, f: resample2d(a, f, implementation="jnp").sum(), argnums=(0, 1))(x, flow)
    g2 = jax.grad(
        lambda a, f: resample2d(a, f, implementation="pallas_interpret").sum(), argnums=(0, 1)
    )(x, flow)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["jnp", "pallas_interpret"])
@pytest.mark.parametrize("p", [1, 2])
def test_channelnorm(rng, impl, p):
    if impl == "pallas_interpret" and p == 1:
        pytest.skip("pallas kernel parameterized test covered by p=2")
    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    got = np.asarray(channelnorm(jnp.asarray(x), p=p, implementation=impl))
    want = (np.abs(x) ** p).sum(-1, keepdims=True) ** (1.0 / p)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["jnp", "mxu", "pallas_interpret"])
def test_correlation(rng, impl):
    x1 = rng.randn(2, 6, 7, 4).astype(np.float32)
    x2 = rng.randn(2, 6, 7, 4).astype(np.float32)
    got = np.asarray(
        correlation(
            jnp.asarray(x1), jnp.asarray(x2), pad_size=2, max_displacement=2, stride2=1,
            implementation=impl,
        )
    )
    want = np_correlation(x1, x2, pad=2, md=2, s2=1)
    assert got.shape == want.shape == (2, 6, 7, 25)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("impl", ["jnp", "mxu"])
def test_correlation_stride2(rng, impl):
    x1 = rng.randn(1, 5, 5, 3).astype(np.float32)
    x2 = rng.randn(1, 5, 5, 3).astype(np.float32)
    got = np.asarray(
        correlation(jnp.asarray(x1), jnp.asarray(x2), pad_size=4, max_displacement=4, stride2=2,
                    implementation=impl)
    )
    want = np_correlation(x1, x2, pad=4, md=4, s2=2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_correlation_mxu_matches_jnp_flownetc_shape(rng):
    """The MXU matmul+band-gather formulation must be bit-comparable to
    the scan path at the FlowNetC operating configuration."""
    x1 = rng.randn(1, 8, 12, 16).astype(np.float32)
    x2 = rng.randn(1, 8, 12, 16).astype(np.float32)
    kw = dict(pad_size=20, max_displacement=20, stride2=2)
    a = np.asarray(correlation(jnp.asarray(x1), jnp.asarray(x2),
                               implementation="jnp", **kw))
    b = np.asarray(correlation(jnp.asarray(x1), jnp.asarray(x2),
                               implementation="mxu", **kw))
    assert a.shape == b.shape == (1, 8, 12, 441)
    np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-5)


def test_correlation_auto_guard_indivisible_displacement(rng):
    """auto must NOT pick mxu when max_displacement % stride2 != 0 (the
    band grid would drop the +md displacement); explicit mxu refuses."""
    x1 = rng.randn(1, 5, 5, 3).astype(np.float32)
    x2 = rng.randn(1, 5, 5, 3).astype(np.float32)
    got = np.asarray(correlation(jnp.asarray(x1), jnp.asarray(x2),
                                 pad_size=5, max_displacement=5, stride2=2,
                                 implementation="auto"))
    want = np_correlation(x1, x2, pad=5, md=5, s2=2)
    assert got.shape == want.shape  # scan-grid channel count (6x6)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    with pytest.raises(NotImplementedError, match="divisible"):
        correlation(jnp.asarray(x1), jnp.asarray(x2), pad_size=5,
                    max_displacement=5, stride2=2, implementation="mxu")

"""Goldens for the fused SPADE norm->modulate epilogue (ISSUE 16).

The numpy reference below re-derives the epilogue independently of the
jnp/fused/pallas implementations: biased instance-norm statistics over
the spatial axes in float64, then ``y = x_hat * (1 + sum(g)) + sum(b)``.
Layer tests pin the integration contract: fused vs unfused is invisible
to everything but the compiler — same outputs, same param tree, same
checkpoint bytes, and the refusal cases (masked partial path, non-
instance base, broadcast maps) fall back to the reference composition.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import serialization

from imaginaire_tpu.layers.activation_norm import (
    AdaptiveNorm,
    HyperSpatiallyAdaptiveNorm,
    SpatiallyAdaptiveNorm,
)
from imaginaire_tpu.ops import spade_modulation
from imaginaire_tpu.ops.spade_modulation import AUTO_IMPLEMENTATION

# downscaled-channel stand-ins for the spade-128/256/512 pyramid levels
# (full-channel operating points are OPSBENCH's job); the last is the
# multi-cond accumulation case (seg + edge + prior-frame maps)
SHAPES = [((2, 32, 32, 8), 1),    # spade-128 deep block
          ((2, 16, 16, 12), 2),   # spade-256 deep block, 2 conditions
          ((1, 64, 64, 4), 3)]    # spade-512 mid block, 3 conditions


def np_spade(x, gammas, betas, eps=1e-5):
    x64 = x.astype(np.float64)
    mean = x64.mean(axis=(1, 2), keepdims=True)
    var = x64.var(axis=(1, 2), keepdims=True)  # biased, like the layer
    xhat = (x64 - mean) / np.sqrt(var + eps)
    g = np.sum([gi.astype(np.float64) for gi in gammas], axis=0)
    b = np.sum([bi.astype(np.float64) for bi in betas], axis=0)
    return (xhat * (1.0 + g) + b).astype(np.float32)


def _case(rng, shape, n_pairs, dtype=np.float32):
    x = rng.randn(*shape).astype(dtype)
    gs = [(rng.randn(*shape) * 0.1).astype(dtype) for _ in range(n_pairs)]
    bs = [(rng.randn(*shape) * 0.1).astype(dtype) for _ in range(n_pairs)]
    return x, gs, bs


@pytest.mark.parametrize("shape,n_pairs", SHAPES)
@pytest.mark.parametrize("impl", ["jnp", "fused", "pallas_interpret"])
def test_forward_matches_reference(rng, impl, shape, n_pairs):
    if impl == "pallas_interpret" and shape[1] > 32:
        pytest.skip("interpret-mode grid too slow at the larger probe")
    x, gs, bs = _case(rng, shape, n_pairs)
    got = np.asarray(spade_modulation(
        jnp.asarray(x), [jnp.asarray(g) for g in gs],
        [jnp.asarray(b) for b in bs], implementation=impl))
    np.testing.assert_allclose(got, np_spade(x, gs, bs),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("shape,n_pairs", SHAPES[:2])
@pytest.mark.parametrize("impl", ["fused", "pallas_interpret"])
def test_grad_matches_jnp_autodiff(rng, impl, shape, n_pairs):
    """The hand-written custom_vjp (incl. the kernel-forward variant)
    must match XLA autodiff through the jnp composition, for dx and
    every dgamma_i/dbeta_i of the multi-cond accumulation."""
    x, gs, bs = _case(rng, shape, n_pairs)
    args = (jnp.asarray(x), tuple(jnp.asarray(g) for g in gs),
            tuple(jnp.asarray(b) for b in bs))

    def loss(impl_):
        def f(x_, gs_, bs_):
            out = spade_modulation(x_, gs_, bs_, implementation=impl_)
            return jnp.sum(jnp.sin(out))  # non-trivial cotangent
        return f

    want = jax.grad(loss("jnp"), argnums=(0, 1, 2))(*args)
    got = jax.grad(loss(impl), argnums=(0, 1, 2))(*args)
    for w, g in zip(jax.tree_util.tree_leaves(want),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("impl", ["jnp", "fused", "pallas_interpret"])
def test_bf16_inputs_fp32_stats(rng, impl):
    """bf16 compute dtype: stats still reduce in fp32 (the norm_stats
    island guard executes inside every implementation), the output stays
    bf16, and values track the f32 reference at bf16 resolution."""
    shape, n_pairs = (2, 16, 16, 8), 2
    x, gs, bs = _case(rng, shape, n_pairs)
    to_bf = lambda a: jnp.asarray(a).astype(jnp.bfloat16)  # noqa: E731
    out = jax.jit(
        lambda x_, gs_, bs_: spade_modulation(
            x_, gs_, bs_, implementation=impl)
    )(to_bf(x), tuple(map(to_bf, gs)), tuple(map(to_bf, bs)))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np_spade(x, gs, bs), rtol=0.1, atol=0.1)


def test_fused_bf16_grad_dtypes(rng):
    x, gs, bs = _case(rng, (2, 8, 8, 4), 2)
    to_bf = lambda a: jnp.asarray(a).astype(jnp.bfloat16)  # noqa: E731
    dx, dgs, dbs = jax.grad(
        lambda x_, gs_, bs_: jnp.sum(spade_modulation(
            x_, gs_, bs_, implementation="fused").astype(jnp.float32)),
        argnums=(0, 1, 2),
    )(to_bf(x), tuple(map(to_bf, gs)), tuple(map(to_bf, bs)))
    assert dx.dtype == jnp.bfloat16
    assert all(t.dtype == jnp.bfloat16 for t in dgs + dbs)


def test_validation_errors(rng):
    x = jnp.asarray(rng.randn(2, 4, 4, 3).astype(np.float32))
    g = jnp.asarray(rng.randn(2, 4, 4, 3).astype(np.float32))
    with pytest.raises(ValueError, match="NHWC"):
        spade_modulation(x[0], [g[0]], [g[0]])
    with pytest.raises(ValueError, match="matched non-empty"):
        spade_modulation(x, [], [])
    with pytest.raises(ValueError, match="matched non-empty"):
        spade_modulation(x, [g, g], [g])
    with pytest.raises(ValueError, match="refusal"):
        spade_modulation(x, [g[:, :1, :1]], [g[:, :1, :1]])
    with pytest.raises(ValueError, match="unknown implementation"):
        spade_modulation(x, [g], [g], implementation="cuda")


# ---------------------------------------------------------------- layers


def _spade_layer(fused, **kw):
    return SpatiallyAdaptiveNorm(
        num_filters=8, base_norm=kw.pop("base_norm", "instance"),
        fused_modulation=fused, **kw)


def test_layer_fused_matches_unfused_multicond(rng, key):
    """SpatiallyAdaptiveNorm: fusing the whole multi-cond accumulation
    changes nothing observable — identical params, identical output."""
    x = jnp.asarray(rng.randn(2, 16, 16, 8).astype(np.float32))
    c1 = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    c2 = jnp.asarray(rng.randn(2, 16, 16, 5).astype(np.float32))
    outs, trees = {}, {}
    for fused in ("fused", "none"):
        layer = _spade_layer(fused)
        params = layer.init(key, x, c1, c2)
        outs[fused] = layer.apply(params, x, c1, c2)
        trees[fused] = params
    assert jax.tree_util.tree_structure(trees["fused"]) \
        == jax.tree_util.tree_structure(trees["none"])
    # same init key + same tree -> checkpoint bytes must be identical:
    # a checkpoint written unfused restores into the fused model
    assert serialization.to_bytes(trees["fused"]) \
        == serialization.to_bytes(trees["none"])
    np.testing.assert_allclose(np.asarray(outs["fused"]),
                               np.asarray(outs["none"]),
                               rtol=1e-5, atol=1e-6)


def test_layer_partial_mask_refuses_to_fuse(rng, key):
    """partial=True with a mask stays on the reference composition:
    fused on/off must be bitwise the same code path."""
    x = jnp.asarray(rng.randn(2, 8, 8, 6).astype(np.float32))
    cond = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    mask = jnp.asarray((rng.rand(2, 8, 8, 1) > 0.5).astype(np.float32))
    outs = {}
    for fused in ("fused", "none"):
        layer = _spade_layer(fused, partial=True)
        params = layer.init(key, x, (cond, mask))
        outs[fused] = layer.apply(params, x, (cond, mask))
    np.testing.assert_array_equal(np.asarray(outs["fused"]),
                                  np.asarray(outs["none"]))


def test_layer_sync_batch_base_refuses_to_fuse(rng, key):
    """The op implements instance statistics only; a sync_batch base
    (the cocostuff SPADE configs) must fall back identically."""
    x = jnp.asarray(rng.randn(2, 8, 8, 6).astype(np.float32))
    cond = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    outs = {}
    for fused in ("fused", "none"):
        layer = _spade_layer(fused, base_norm="sync_batch")
        params = layer.init(key, x, cond)
        outs[fused] = layer.apply(params, x, cond, training=True,
                                  mutable=["batch_stats"])[0]
    np.testing.assert_array_equal(np.asarray(outs["fused"]),
                                  np.asarray(outs["none"]))


def test_hyper_layer_runtime_weight_path(rng, key):
    """HyperSpatiallyAdaptiveNorm: the first pair — produced by the
    predicted per-sample conv — fuses with the norm; later pairs apply
    sequentially. Fused on/off must agree with identical params."""
    b, c, cc = 2, 6, 4
    x = jnp.asarray(rng.randn(b, 8, 8, c).astype(np.float32))
    cond0 = jnp.asarray(rng.randn(b, 8, 8, cc).astype(np.float32))
    cond1 = jnp.asarray(rng.randn(b, 8, 8, 3).astype(np.float32))
    w = jnp.asarray((rng.randn(b, 3, 3, cc, 2 * c) * 0.1)
                    .astype(np.float32))
    bias = jnp.asarray((rng.randn(b, 2 * c) * 0.1).astype(np.float32))
    outs, trees = {}, {}
    for fused in ("fused", "none"):
        layer = HyperSpatiallyAdaptiveNorm(base_norm="instance",
                                           fused_modulation=fused)
        params = layer.init(key, x, cond0, cond1, norm_weights=(w, bias))
        outs[fused] = layer.apply(params, x, cond0, cond1,
                                  norm_weights=(w, bias))
        trees[fused] = params
    assert serialization.to_bytes(trees["fused"]) \
        == serialization.to_bytes(trees["none"])
    np.testing.assert_allclose(np.asarray(outs["fused"]),
                               np.asarray(outs["none"]),
                               rtol=1e-5, atol=1e-6)


def test_adaptive_norm_conv_fuses_linear_refuses(rng, key):
    """AdaptiveNorm: the 'conv' projection emits full-spatial maps and
    fuses; the 'linear' projection's broadcast (B,1,1,C) maps hit the
    op's shape refusal and stay on the reference composition."""
    x = jnp.asarray(rng.randn(2, 8, 8, 6).astype(np.float32))
    style = jnp.asarray(rng.randn(2, 16).astype(np.float32))
    cond = jnp.asarray(rng.randn(2, 8, 8, 16).astype(np.float32))
    for projection, c in (("conv", cond), ("linear", style)):
        outs = {}
        for fused in ("fused", "none"):
            layer = AdaptiveNorm(projection=projection,
                                 base_norm="instance",
                                 fused_modulation=fused)
            params = layer.init(key, x, c)
            outs[fused] = layer.apply(params, x, c)
        np.testing.assert_allclose(np.asarray(outs["fused"]),
                                   np.asarray(outs["none"]),
                                   rtol=1e-5, atol=1e-6)


# ------------------------------------------------- decision-table pins


def test_auto_pin_backed_by_opsbench():
    """AUTO_IMPLEMENTATION constants must agree with the committed
    OPSBENCH.json decision table (the refresh protocol in
    ops/__init__.py) — and the spade pin must be backed by clean
    measured rows, not asserted by fiat."""
    from imaginaire_tpu import ops

    path = os.path.join(os.path.dirname(__file__), "..", "OPSBENCH.json")
    with open(path) as f:
        table = json.load(f)
    resolved = ops.resolved_implementations()
    for op, impl in resolved.items():
        assert table["winners"].get(op) == impl, (
            f"{op}: AUTO_IMPLEMENTATION={impl!r} but OPSBENCH winner is "
            f"{table['winners'].get(op)!r} — re-run scripts/opsbench.py "
            f"and update the pin together")
    rows = [c for c in table["cases"]
            if c["op"] == "spade_modulation"
            and c["impl"] == resolved["spade_modulation"]]
    assert rows and all("ms" in r for r in rows)
    # the spade rows carry the decision axis for a residual-policy op
    assert all("temp_bytes" in r for r in rows)


def test_auto_dispatch_resolves(rng):
    x, gs, bs = _case(rng, (1, 8, 8, 4), 1)
    a = spade_modulation(jnp.asarray(x), [jnp.asarray(gs[0])],
                         [jnp.asarray(bs[0])], implementation="auto")
    b = spade_modulation(jnp.asarray(x), [jnp.asarray(gs[0])],
                         [jnp.asarray(bs[0])],
                         implementation=AUTO_IMPLEMENTATION)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert AUTO_IMPLEMENTATION in ("jnp", "fused", "pallas")

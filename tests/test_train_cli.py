"""CLI-level end-to-end training contract
(ref: scripts/test_training.sh:16-66 — the reference's top-level test
runs train.py itself for 2 iterations per algorithm).

Each case subprocess-runs ``python train.py --config
configs/unit_test/<x>.yaml`` on the tiny fixtures, then re-invokes with
the same logdir to prove the latest_checkpoint.txt resume leg: the
second run must restore iteration 2 and exit immediately at max_iter.
"""

import glob
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(__file__)
ROOT = os.path.abspath(os.path.join(HERE, ".."))


def _test_env():
    return dict(os.environ,
                JAX_PLATFORMS="cpu",
                XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"),
                JAX_COMPILATION_CACHE_DIR="/tmp/jax_test_cache")


def _run_train(config, logdir, max_iter=2):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "train.py"),
         "--config", os.path.join(ROOT, "configs", "unit_test", config),
         "--logdir", logdir, "--max_iter", str(max_iter), "--seed", "0"],
        capture_output=True, text=True, cwd=ROOT, timeout=1200,
        env=_test_env())


@pytest.mark.slow
@pytest.mark.parametrize("config", ["spade.yaml", "vid2vid_street.yaml"])
def test_train_cli_two_iters_then_resume(config, tmp_path):
    logdir = str(tmp_path / "log")
    r = _run_train(config, logdir)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Done with training!!!" in r.stdout

    # checkpoint + pointer file written
    pointer = glob.glob(os.path.join(logdir, "**", "latest_checkpoint.txt"),
                        recursive=True)
    assert pointer, os.listdir(logdir)

    # resume leg: restores iteration 2 and stops at max_iter immediately
    r2 = _run_train(config, logdir)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "Done with training!!!" in r2.stdout


@pytest.mark.slow
def test_train_cli_bad_config_fails_loudly(tmp_path):
    r = _run_train("definitely_missing.yaml", str(tmp_path / "log"))
    assert r.returncode != 0


@pytest.mark.slow
def test_evaluate_cli_end_to_end(tmp_path):
    """train.py 2 iters -> evaluate.py --checkpoint --metrics kid,prdc
    (random-init inception via a derived config), plus the loud failure
    when the metrics can't be produced (no weights, no random_init)."""
    import yaml

    logdir = str(tmp_path / "log")
    base = os.path.join(ROOT, "configs", "unit_test", "spade.yaml")
    r = _run_train("spade.yaml", logdir)
    assert r.returncode == 0, r.stderr[-2000:]
    pointer = glob.glob(os.path.join(logdir, "latest_checkpoint.txt"))
    assert pointer
    with open(pointer[0]) as f:
        ckpt_path = os.path.join(logdir, f.read().strip())

    with open(base) as f:
        cfg = yaml.safe_load(f)
    cfg["trainer"]["fid_random_init"] = True  # metric plumbing test only
    derived = str(tmp_path / "spade_eval.yaml")
    with open(derived, "w") as f:
        yaml.safe_dump(cfg, f)

    def run_eval(config):
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "evaluate.py"),
             "--config", config, "--logdir", str(tmp_path / "eval"),
             "--checkpoint", ckpt_path, "--metrics", "kid,prdc"],
            capture_output=True, text=True, cwd=ROOT, timeout=1200,
            env=_test_env())

    r2 = run_eval(derived)
    assert r2.returncode == 0, r2.stdout[-800:] + r2.stderr[-1200:]
    assert "KID:" in r2.stdout and "PRDC_precision:" in r2.stdout, \
        r2.stdout[-800:]

    # without weights or random_init the sweep must fail loudly (only
    # meaningful where no converted inception weights are provisioned)
    from imaginaire_tpu.evaluation.inception import DEFAULT_WEIGHTS

    if os.path.exists(DEFAULT_WEIGHTS):
        pytest.skip("converted inception weights present: the no-weights "
                    "failure leg is unreachable")
    r3 = run_eval(base)
    assert r3.returncode != 0
    assert "produced none" in (r3.stdout + r3.stderr)

"""CLI-level end-to-end training contract
(ref: scripts/test_training.sh:16-66 — the reference's top-level test
runs train.py itself for 2 iterations per algorithm).

Each case subprocess-runs ``python train.py --config
configs/unit_test/<x>.yaml`` on the tiny fixtures, then re-invokes with
the same logdir to prove the latest_checkpoint.txt resume leg: the
second run must restore iteration 2 and exit immediately at max_iter.
"""

import glob
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(__file__)
ROOT = os.path.abspath(os.path.join(HERE, ".."))


def _test_env():
    return dict(os.environ,
                JAX_PLATFORMS="cpu",
                XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"),
                JAX_COMPILATION_CACHE_DIR="/tmp/jax_test_cache")


def _run_train(config, logdir, max_iter=2):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "train.py"),
         "--config", os.path.join(ROOT, "configs", "unit_test", config),
         "--logdir", logdir, "--max_iter", str(max_iter), "--seed", "0"],
        capture_output=True, text=True, cwd=ROOT, timeout=1200,
        env=_test_env())


@pytest.fixture(scope="module")
def spade_checkpoint(tmp_path_factory):
    """One shared 2-iter spade training run for the evaluate/inference
    CLI tests (the resume test trains its own logdir — re-invoking
    train.py there mutates it)."""
    logdir = str(tmp_path_factory.mktemp("spade_cli") / "log")
    r = _run_train("spade.yaml", logdir)
    assert r.returncode == 0, r.stderr[-2000:]
    with open(os.path.join(logdir, "latest_checkpoint.txt")) as f:
        return os.path.join(logdir, f.read().strip())


@pytest.mark.slow
@pytest.mark.parametrize("config", ["spade.yaml", "vid2vid_street.yaml"])
def test_train_cli_two_iters_then_resume(config, tmp_path):
    logdir = str(tmp_path / "log")
    r = _run_train(config, logdir)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Done with training!!!" in r.stdout

    # checkpoint + pointer file written
    pointer = glob.glob(os.path.join(logdir, "**", "latest_checkpoint.txt"),
                        recursive=True)
    assert pointer, os.listdir(logdir)

    # resume leg: restores iteration 2 and stops at max_iter immediately
    r2 = _run_train(config, logdir)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "Done with training!!!" in r2.stdout


@pytest.mark.slow
def test_train_cli_bad_config_fails_loudly(tmp_path):
    r = _run_train("definitely_missing.yaml", str(tmp_path / "log"))
    assert r.returncode != 0


@pytest.mark.slow
def test_evaluate_cli_end_to_end(spade_checkpoint, tmp_path):
    """train.py 2 iters -> evaluate.py --checkpoint --metrics kid,prdc
    (random-init inception via a derived config), plus the loud failure
    when the metrics can't be produced (no weights, no random_init)."""
    import yaml

    base = os.path.join(ROOT, "configs", "unit_test", "spade.yaml")
    ckpt_path = spade_checkpoint

    with open(base) as f:
        cfg = yaml.safe_load(f)
    cfg["trainer"]["fid_random_init"] = True  # metric plumbing test only
    derived = str(tmp_path / "spade_eval.yaml")
    with open(derived, "w") as f:
        yaml.safe_dump(cfg, f)

    def run_eval(config):
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "evaluate.py"),
             "--config", config, "--logdir", str(tmp_path / "eval"),
             "--checkpoint", ckpt_path, "--metrics", "kid,prdc"],
            capture_output=True, text=True, cwd=ROOT, timeout=1200,
            env=_test_env())

    r2 = run_eval(derived)
    assert r2.returncode == 0, r2.stdout[-800:] + r2.stderr[-1200:]
    assert "KID:" in r2.stdout and "PRDC_precision:" in r2.stdout, \
        r2.stdout[-800:]


@pytest.mark.slow
def test_evaluate_cli_fails_loudly_without_weights(spade_checkpoint,
                                                   tmp_path):
    """Without converted inception weights or fid_random_init, the sweep
    must exit non-zero instead of reporting a silent partial result."""
    from imaginaire_tpu.evaluation.inception import DEFAULT_WEIGHTS

    if os.path.exists(DEFAULT_WEIGHTS):
        pytest.skip("converted inception weights present: the no-weights "
                    "failure leg is unreachable")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "evaluate.py"),
         "--config", os.path.join(ROOT, "configs", "unit_test",
                                  "spade.yaml"),
         "--logdir", str(tmp_path / "eval"),
         "--checkpoint", spade_checkpoint, "--metrics", "kid,prdc"],
        capture_output=True, text=True, cwd=ROOT, timeout=1200,
        env=_test_env())
    assert r.returncode != 0
    assert "produced none" in (r.stdout + r.stderr)


@pytest.mark.slow
def test_inference_cli_end_to_end(spade_checkpoint, tmp_path):
    """Shared 2-iter checkpoint -> inference.py writes images for every
    test item (ref: the reference's inference entry contract)."""
    ckpt_path = spade_checkpoint
    out_dir = str(tmp_path / "out")
    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "inference.py"),
         "--config", os.path.join(ROOT, "configs", "unit_test", "spade.yaml"),
         "--checkpoint", ckpt_path, "--output_dir", out_dir,
         "--logdir", str(tmp_path / "inflog")],
        capture_output=True, text=True, cwd=ROOT, timeout=1200,
        env=_test_env())
    assert r2.returncode == 0, r2.stdout[-500:] + r2.stderr[-1500:]
    assert "Done with inference" in r2.stdout
    images = [f for dp, _, fs in os.walk(out_dir)
              for f in fs if f.endswith((".jpg", ".png"))]
    assert images, f"no images written under {out_dir}"


@pytest.mark.slow
def test_inference_cli_ring_attention_matches_unsharded(tmp_path):
    """User-facing ring attention (VERDICT r3 #8): inference.py on the
    attn config over a (2, 4) data x seq mesh of 8 virtual devices must
    write the same frames as the unsharded twin — the non_local block's
    token axis is sharded over 'seq' (parallel/ring_attention.py), so
    feature maps larger than one device's memory scale across the ring
    while the numerics stay put (same param tree, same seed)."""
    import cv2
    import numpy as np
    import yaml

    base = os.path.join(ROOT, "configs", "unit_test", "spade.yaml")
    outs = {}
    for variant, ring in (("ring", "seq"), ("plain", "")):
        with open(base) as f:
            cfg = yaml.safe_load(f)
        cfg["gen"]["non_local"] = {"enabled": True, "ring_axis": ring}
        if ring:
            cfg["runtime"] = {"mesh": {"axes": ["data", "seq"],
                                       "shape": [2, 4]}}
        derived = str(tmp_path / f"spade_{variant}.yaml")
        with open(derived, "w") as f:
            yaml.safe_dump(cfg, f)
        out_dir = str(tmp_path / f"out_{variant}")
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "inference.py"),
             "--config", derived, "--output_dir", out_dir,
             "--logdir", str(tmp_path / f"log_{variant}"), "--seed", "0"],
            capture_output=True, text=True, cwd=ROOT, timeout=1200,
            env=_test_env())
        assert r.returncode == 0, r.stdout[-500:] + r.stderr[-1500:]
        images = sorted(os.path.join(dp, f)
                        for dp, _, fs in os.walk(out_dir) for f in fs
                        if f.endswith((".jpg", ".png")))
        assert images, f"no images written under {out_dir}"
        outs[variant] = images

    assert [os.path.relpath(p, tmp_path / "out_ring")
            for p in outs["ring"]] == \
        [os.path.relpath(p, tmp_path / "out_plain")
         for p in outs["plain"]]
    for ring_img, plain_img in zip(outs["ring"], outs["plain"]):
        a = cv2.imread(ring_img).astype(np.float32)
        b = cv2.imread(plain_img).astype(np.float32)
        # identical up to ring-summation float order + jpeg encode
        assert np.mean(np.abs(a - b)) < 1.5, (ring_img, np.mean(np.abs(a - b)))
        assert np.max(np.abs(a - b)) < 24, (ring_img, np.max(np.abs(a - b)))

"""Pod observability plane (ISSUE 17): digest publish/aggregate over
the coordination KV, cross-host skew math, the SPMD divergence
sentinel, straggler attribution (live-slow, stale, and desync paths),
the merged pod timeline, and the new check_run_health gates.

Like test_cluster.py, the live plane runs against the in-memory fake of
the jax coordination-service KV client
(``cluster.set_client_for_testing``) — two "processes" are simulated by
switching the fake topology's process index between publishes against
one shared KV dict. The dryrun ``spade_pod`` leg covers the real-pod
end-to-end path.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from imaginaire_tpu import telemetry
from imaginaire_tpu.resilience import chaos, cluster
from imaginaire_tpu.telemetry import podview
from imaginaire_tpu.telemetry.report import summarize


class FakeClient:
    """In-memory stand-in for jaxlib's DistributedRuntimeClient KV
    surface (the PR-8 test seam; barrier untested here)."""

    def __init__(self, n):
        self.n = n
        self.kv = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        if key in self.kv and not allow_overwrite:
            raise RuntimeError(f"key exists: {key}")
        self.kv[key] = value

    def key_value_dir_get(self, prefix):
        return sorted((k, v) for k, v in self.kv.items()
                      if k.startswith(prefix))

    def key_value_delete(self, key):
        self.kv.pop(key, None)

    def wait_at_barrier(self, barrier_id, timeout_ms, process_ids=None):
        pass


SETTINGS = {
    "enabled": True,
    "digest_every_n_steps": 1,
    "history": 8,
    "divergence": "crc",
    "ewma_rel_threshold": 0.05,
    "stale_after_s": 0.0,
}


@pytest.fixture(autouse=True)
def _reset():
    yield
    cluster.set_client_for_testing(None)
    cluster._SETTINGS = None
    podview.configure(None)
    chaos._CHAOS = chaos._NULL


@pytest.fixture
def tm():
    t = telemetry.configure(cfg=None, enabled=True, sinks=[],
                            flush_every_n_steps=0, mfu=False)
    # configure(cfg=None) auto-installs a null podview; tests install
    # their own explicitly
    yield t


def _events(tm, kind=None, name=None):
    with tm._lock:
        evs = list(tm._events)
    return [e for e in evs
            if (kind is None or e.get("kind") == kind)
            and (name is None or e.get("name") == name)]


def _publish_as(client, proc, n, settings=None, losses=None, step=1,
                view=None):
    """Publish one digest as process ``proc`` against the shared KV;
    returns the PodView used (pass ``view`` to keep one across steps)."""
    cluster.set_client_for_testing(client, process_index=proc,
                                   process_count=n)
    if view is None:
        view = podview.PodView(dict(settings or SETTINGS))
    podview._PODVIEW = view
    if losses is not None:
        view.note_losses(step, "G", losses)
    view.on_step(step)
    return view


# ------------------------------------------------- publish / aggregate


class TestDigestPublish:
    def test_publish_writes_epoch_scoped_key_and_local_meta(self, tm):
        client = FakeClient(2)
        _publish_as(client, 0, 2, losses={"total": 1.0})
        assert "pod/p0" in client.kv
        hist = json.loads(client.kv["pod/p0"])
        assert isinstance(hist, list) and hist[-1]["step"] == 1
        assert hist[-1]["loss_crc"] is not None
        assert "spans" in hist[-1] and "collective" in hist[-1]["spans"]
        # the digest is mirrored into the local jsonl stream — the
        # post-hoc merge's parse target
        metas = _events(tm, "meta", "pod/digest")
        assert len(metas) == 1 and metas[0]["step"] == 1

    def test_digest_cadence(self, tm):
        client = FakeClient(1)
        settings = dict(SETTINGS, digest_every_n_steps=5)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=1)
        view = podview.PodView(settings)
        podview._PODVIEW = view
        for step in range(1, 11):
            view.on_step(step)
        hist = json.loads(client.kv["pod/p0"])
        assert [d["step"] for d in hist] == [5, 10]

    def test_history_bounded(self, tm):
        client = FakeClient(1)
        settings = dict(SETTINGS, history=3)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=1)
        view = podview.PodView(settings)
        podview._PODVIEW = view
        for step in range(1, 6):
            view.on_step(step)
        hist = json.loads(client.kv["pod/p0"])
        assert [d["step"] for d in hist] == [3, 4, 5]

    def test_every_process_emits_counters(self, tm):
        # the --hosts gate reads per-process files: BOTH processes must
        # emit skew/divergence counters into their own streams once the
        # pod is fully published
        client = FakeClient(2)
        _publish_as(client, 1, 2, losses={"total": 1.0})
        _publish_as(client, 0, 2, losses={"total": 1.0})
        # p0 (published last, sees both) has the full set
        assert _events(tm, "counter", "pod/step_skew_ms")
        assert _events(tm, "counter", "pod/divergence")
        assert _events(tm, "meta", "pod/straggler")

    def test_aggregate_uses_newest_common_step(self, tm):
        # peers at different digest phases: the skew round runs at the
        # newest step BOTH have published, not the global newest
        client = FakeClient(2)
        now = time.time()
        client.kv["pod/p1"] = json.dumps([
            {"step": 1, "t": now - 0.5, "spans": {}, "loss_crc": 1,
             "loss_val": 1.0},
            {"step": 2, "t": now - 0.2, "spans": {}, "loss_crc": 1,
             "loss_val": 1.0},
        ])
        view = _publish_as(client, 0, 2, losses={"total": 1.0}, step=2)
        skews = _events(tm, "counter", "pod/step_skew_ms")
        assert len(skews) == 1 and skews[0]["step"] == 2
        # only steps BOTH hosts published are divergence-checkable
        assert view._checked_steps == {2}


class TestSkewMath:
    def test_skew_vs_hand_computed_timeline(self, tm):
        # p1's digest for step 1 is stamped 250ms before ours -> the
        # skew at the common step is ~250ms and p0 (later t) is slowest
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        view = podview.PodView(dict(SETTINGS))
        podview._PODVIEW = view
        client.kv["pod/p1"] = json.dumps([
            {"step": 1, "t": time.time() - 0.25, "spans": {},
             "loss_crc": None, "loss_val": None}])
        view.on_step(1)
        skew = _events(tm, "counter", "pod/step_skew_ms")[0]
        assert skew["value"] == pytest.approx(250.0, abs=100.0)
        straggler = _events(tm, "meta", "pod/straggler")[0]
        assert straggler["process"] == 0
        assert _events(tm, "counter", "pod/straggler/p0")

    def test_dominant_span_is_largest_excess_over_median(self):
        recs = {
            0: {"spans": {"data_wait": 5.0, "dis_step": 10.0,
                          "gen_step": 10.0, "collective": 1.0}},
            1: {"spans": {"data_wait": 90.0, "dis_step": 12.0,
                          "gen_step": 11.0, "collective": 2.0}},
            2: {"spans": {"data_wait": 6.0, "dis_step": 11.0,
                          "gen_step": 10.0, "collective": 1.0}},
        }
        assert podview.PodView._dominant_span(recs, 1) == "data_wait"

    def test_collective_wait_accumulates_into_digest(self, tm):
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        view = podview.PodView(dict(SETTINGS))
        podview._PODVIEW = view
        view.note_collective_wait(12.5)
        view.note_collective_wait(7.5)
        view.on_step(1)
        hist = json.loads(client.kv["pod/p0"])
        assert hist[-1]["spans"]["collective"] == pytest.approx(20.0)
        # and the accumulator resets for the next digest window
        view.on_step(2)
        hist = json.loads(client.kv["pod/p0"])
        assert hist[-1]["spans"]["collective"] == 0.0

    def test_timed_barrier_feeds_collective_wait(self, tm):
        # the PR-8 arrival spreads feed podview for free: a barrier
        # where the peer arrived earlier credits our wait as ~0, a
        # barrier where the peer arrives later credits the spread
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        view = podview.PodView(dict(SETTINGS))
        podview._PODVIEW = view
        # peer arrived 40ms after us: our key is written by
        # timed_barrier itself; pre-plant the peer's late arrival
        client.kv["arrive/sync:t0/p1"] = f"{time.time() + 0.04:.3f}"
        cluster.timed_barrier("sync", timeout_s=5, tag="t0")
        assert view._collective_ms == pytest.approx(40.0, abs=30.0)


# --------------------------------------------------- divergence sentinel


class TestDivergenceSentinel:
    def test_silent_on_bit_identical_runs(self, tm):
        client = FakeClient(2)
        _publish_as(client, 1, 2, losses={"total": 1.2345678901234567})
        _publish_as(client, 0, 2, losses={"total": 1.2345678901234567})
        assert not _events(tm, "meta", "pod/divergence")
        counters = _events(tm, "counter", "pod/divergence")
        assert counters and all(c["value"] == 0 for c in counters)

    def test_fires_on_flipped_loss_crc(self, tm):
        client = FakeClient(2)
        _publish_as(client, 1, 2, losses={"total": 1.0000001})
        _publish_as(client, 0, 2, losses={"total": 1.0})
        metas = _events(tm, "meta", "pod/divergence")
        assert len(metas) == 1 and metas[0]["mode"] == "crc"
        assert metas[0]["crcs"]["p0"] != metas[0]["crcs"]["p1"]
        counters = _events(tm, "counter", "pod/divergence")
        assert counters[-1]["value"] == 1

    def test_each_step_checked_once(self, tm):
        # re-aggregating the same histories must not double-count
        client = FakeClient(2)
        _publish_as(client, 1, 2, losses={"total": 2.0})
        view = _publish_as(client, 0, 2, losses={"total": 1.0})
        view._aggregate(view._history[-1])
        counters = _events(tm, "counter", "pod/divergence")
        assert counters[-1]["value"] == 1

    def test_chaos_injection_trips_crc(self, tm):
        # the drill path: chaos perturbs ONE process's observed losses
        # at the digest boundary, the sentinel must notice
        chaos._CHAOS = chaos.ChaosMonkey(chaos.chaos_settings({
            "chaos": {"enabled": True, "diverge_loss_at_step": 1,
                      "diverge_process_index": 1}}))
        client = FakeClient(2)
        _publish_as(client, 1, 2, losses={"total": 1.0})
        _publish_as(client, 0, 2, losses={"total": 1.0})
        metas = _events(tm, "meta", "pod/divergence")
        assert len(metas) == 1 and metas[0]["mode"] == "crc"

    def test_ewma_mode_thresholds_relative_delta(self, tm):
        client = FakeClient(2)
        settings = dict(SETTINGS, divergence="ewma",
                        ewma_rel_threshold=0.05)
        _publish_as(client, 1, 2, settings=settings,
                    losses={"total": 1.0})
        _publish_as(client, 0, 2, settings=settings,
                    losses={"total": 1.5})
        metas = _events(tm, "meta", "pod/divergence")
        assert metas and metas[0]["mode"] == "ewma"

    def test_ewma_mode_tolerates_small_deltas(self, tm):
        client = FakeClient(2)
        settings = dict(SETTINGS, divergence="ewma",
                        ewma_rel_threshold=0.05)
        _publish_as(client, 1, 2, settings=settings,
                    losses={"total": 1.0})
        _publish_as(client, 0, 2, settings=settings,
                    losses={"total": 1.01})
        assert not _events(tm, "meta", "pod/divergence")


class TestDivergenceModeAuto:
    def test_fp32_pure_dp_resolves_to_crc(self):
        s = podview.pod_settings({
            "trainer": {"compute_dtype": "float32"},
            "parallel": {"mesh_shape": None}})
        assert s["divergence"] == "crc"

    def test_bf16_downgrades_to_ewma(self):
        s = podview.pod_settings({
            "trainer": {"compute_dtype": "bfloat16"}})
        assert s["divergence"] == "ewma"

    def test_model_parallel_downgrades_to_ewma(self):
        s = podview.pod_settings({
            "trainer": {"compute_dtype": "float32"},
            "parallel": {"mesh_shape": {"data": 2, "model": 2}}})
        assert s["divergence"] == "ewma"

    def test_explicit_mode_wins(self):
        s = podview.pod_settings({
            "telemetry": {"pod": {"divergence": "crc"}},
            "trainer": {"compute_dtype": "bfloat16"}})
        assert s["divergence"] == "crc"


# ----------------------------------------------- straggler attribution


class TestStragglerAttribution:
    def test_stale_peer_attributed_with_stalled_span(self, tm):
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        settings = dict(SETTINGS, stale_after_s=5.0)
        view = podview.PodView(settings)
        podview._PODVIEW = view
        # p1's last digest is 60s old — it stopped making step progress
        client.kv["pod/p1"] = json.dumps([
            {"step": 3, "t": time.time() - 60.0, "spans": {},
             "loss_crc": None, "loss_val": None}])
        view.on_step(9)
        metas = _events(tm, "meta", "pod/straggler")
        stalled = [m for m in metas if m["process"] == 1]
        assert stalled and stalled[0]["span"] == "stalled"
        assert stalled[0]["last_step"] == 3
        assert _events(tm, "counter", "pod/straggler/p1")

    def test_note_desync_lands_before_flush(self, tm):
        # the barrier-timeout path: attribution must be in the stream
        # (and idempotent per process) before ClusterDesyncError raises
        client = FakeClient(2)
        cluster.set_client_for_testing(client, process_index=0,
                                       process_count=2)
        view = podview.PodView(dict(SETTINGS))
        podview._PODVIEW = view
        view.note_desync([1])
        view.note_desync([1])  # second desync event: same process
        metas = _events(tm, "meta", "pod/straggler")
        assert len(metas) == 1
        assert metas[0]["process"] == 1
        assert metas[0]["span"] == "stalled"
        assert metas[0]["reason"] == "absent_at_barrier"

    def test_status_line_names_laggard(self, tm):
        client = FakeClient(2)
        _publish_as(client, 1, 2)
        view = _publish_as(client, 0, 2)
        line = view.status_line()
        assert line is not None and "p0" in line and "p1" in line
        # and it rides the hang-dump header via the telemetry hook
        assert telemetry.Telemetry._pod_skew_line() == line


# ------------------------------------------------------ post-hoc plane


def _write_host_jsonl(logdir, proc, digests, extra=()):
    path = os.path.join(logdir, f"telemetry.jsonl.p{proc}")
    with open(path, "w") as f:
        for d in digests:
            f.write(json.dumps({"kind": "meta", "name": "pod/digest",
                                "t": d["t"], **d}) + "\n")
        for ev in extra:
            f.write(json.dumps(ev) + "\n")
    return path


def _three_host_fixture(tmp_path, diverge_at=None):
    """Synthetic 3-host pod: p2 is persistently ~100ms late with a fat
    data_wait span; optional crc flip on p1 at ``diverge_at``."""
    t0 = 1_700_000_000.0
    for proc in range(3):
        digests = []
        for step in (1, 2, 3):
            late = 0.1 if proc == 2 else 0.0
            crc = 1111
            if diverge_at is not None and proc == 1 \
                    and step >= diverge_at:
                crc = 2222
            digests.append({
                "step": step,
                "t": t0 + step * 1.0 + late,
                "spans": {"data_wait": 120.0 if proc == 2 else 20.0,
                          "dis_step": 30.0, "gen_step": 40.0,
                          "collective": 5.0},
                "loss_crc": crc, "loss_val": 1.0,
            })
        _write_host_jsonl(str(tmp_path), proc, digests)
    return str(tmp_path)


class TestMergePodTimeline:
    def test_merges_lanes_and_skew(self, tmp_path):
        logdir = _three_host_fixture(tmp_path)
        merged = podview.merge_pod_timeline(logdir)
        assert merged["hosts"] == [0, 1, 2]
        assert set(merged["steps"]) == {1, 2, 3}
        for s in (1, 2, 3):
            entry = merged["steps"][s]
            assert entry["slowest"] == 2
            assert entry["skew_ms"] == pytest.approx(100.0)
        assert merged["skew"]["p50_ms"] == pytest.approx(100.0)
        assert merged["skew"]["rounds"] == 3
        # 100ms lands in the le_100ms bucket
        assert merged["skew"]["hist"]["le_100ms"] == 3

    def test_straggler_table_names_span(self, tmp_path):
        logdir = _three_host_fixture(tmp_path)
        merged = podview.merge_pod_timeline(logdir)
        assert merged["straggler"]["process"] == 2
        assert merged["straggler"]["share"] == 1.0
        assert merged["straggler"]["span"] == "data_wait"
        assert merged["divergence"]["count"] == 0

    def test_divergence_detected_post_hoc(self, tmp_path):
        logdir = _three_host_fixture(tmp_path, diverge_at=2)
        merged = podview.merge_pod_timeline(logdir)
        assert merged["divergence"]["count"] == 2
        assert merged["divergence"]["steps"] == [2, 3]
        assert merged["steps"][2]["diverged"] is True

    def test_render_is_markdown(self, tmp_path):
        logdir = _three_host_fixture(tmp_path, diverge_at=3)
        out = podview.render_pod_timeline(
            podview.merge_pod_timeline(logdir))
        assert "# pod timeline" in out
        assert "straggler: p2" in out
        assert "| step |" in out
        assert "!! divergence" in out

    def test_tolerates_partial_histories(self, tmp_path):
        # p1 died after step 1: steps 2-3 still render from the
        # surviving lanes, skew stats only count full rounds
        t0 = 1_700_000_000.0
        _write_host_jsonl(str(tmp_path), 0, [
            {"step": s, "t": t0 + s, "spans": {}, "loss_crc": 1,
             "loss_val": 1.0} for s in (1, 2, 3)])
        _write_host_jsonl(str(tmp_path), 1, [
            {"step": 1, "t": t0 + 1.05, "spans": {}, "loss_crc": 1,
             "loss_val": 1.0}])
        merged = podview.merge_pod_timeline(str(tmp_path))
        assert set(merged["steps"]) == {1, 2, 3}
        assert merged["skew"]["rounds"] == 1


# ------------------------------------------------------------- gates


def _pod_events(skew_values=(), straggler=None, divergence=0,
                divergence_steps=()):
    evs = [{"kind": "counter", "name": "pod/step_skew_ms", "value": v,
            "step": i + 1, "t": 1.0} for i, v in enumerate(skew_values)]
    if straggler is not None:
        proc, rounds = straggler
        evs.append({"kind": "counter",
                    "name": f"pod/straggler/p{proc}", "value": rounds,
                    "step": 1, "t": 1.0})
        evs.append({"kind": "meta", "name": "pod/straggler", "t": 1.0,
                    "process": proc, "span": "data_wait",
                    "rounds": rounds})
    evs.append({"kind": "counter", "name": "pod/divergence",
                "value": divergence, "step": 1, "t": 1.0})
    for s in divergence_steps:
        evs.append({"kind": "meta", "name": "pod/divergence", "t": 1.0,
                    "step": s, "mode": "crc"})
    return evs


class TestHealthGates:
    def test_clean_pod_passes_all_gates(self):
        from scripts.check_run_health import check_health

        summary = summarize(_pod_events(skew_values=[5.0, 8.0]))
        assert summary["pod"]["present"]
        failures = check_health(summary, max_step_skew_ms=50,
                                max_divergence=0,
                                max_straggler_share=0.9)
        assert failures == []

    def test_skew_gate_thresholds_p50(self):
        from scripts.check_run_health import check_health

        summary = summarize(_pod_events(skew_values=[10.0, 900.0,
                                                     950.0]))
        failures = check_health(summary, max_step_skew_ms=100)
        assert len(failures) == 1 and "step skew" in failures[0]

    def test_divergence_gate_zero_tolerance(self):
        from scripts.check_run_health import check_health

        summary = summarize(_pod_events(divergence=1,
                                        divergence_steps=[4]))
        failures = check_health(summary, max_divergence=0)
        assert len(failures) == 1
        assert "divergence" in failures[0] and "step(s) [4]" in failures[0]

    def test_straggler_share_gate(self):
        from scripts.check_run_health import check_health

        summary = summarize(_pod_events(skew_values=[5.0],
                                        straggler=(2, 9)))
        failures = check_health(summary, max_straggler_share=0.5)
        assert len(failures) == 1 and "straggler" in failures[0]
        assert "p2" in failures[0] and "data_wait" in failures[0]

    def test_runs_without_pod_counters_pass(self):
        from scripts.check_run_health import check_health

        summary = summarize([])
        failures = check_health(summary, max_step_skew_ms=1,
                                max_divergence=0,
                                max_straggler_share=0.1)
        assert failures == []

    def test_hosts_cli_gate_fails_on_divergence(self, tmp_path):
        from scripts.check_run_health import main

        for proc in range(2):
            path = os.path.join(str(tmp_path),
                                f"telemetry.jsonl.p{proc}")
            with open(path, "w") as f:
                for ev in _pod_events(skew_values=[5.0],
                                      divergence=1 if proc == 0 else 0,
                                      divergence_steps=[3]
                                      if proc == 0 else ()):
                    f.write(json.dumps(ev) + "\n")
        assert main([str(tmp_path), "--hosts", "--max-divergence", "0"]
                    ) == 1
        assert main([str(tmp_path), "--hosts", "--max-divergence", "1"]
                    ) == 0

    def test_report_pod_section(self):
        from imaginaire_tpu.telemetry.report import render_report

        out = render_report(_pod_events(skew_values=[5.0, 8.0],
                                        straggler=(1, 3),
                                        divergence=1,
                                        divergence_steps=[2]))
        assert "## pod" in out
        assert "straggler: p1" in out
        assert "divergence sentinel: 1" in out


# ----------------------------------------------------------- satellites


class TestChaosDivergenceKnob:
    def test_perturbs_only_matching_process_and_step(self):
        monkey = chaos.ChaosMonkey(chaos.chaos_settings({
            "chaos": {"enabled": True, "diverge_loss_at_step": 3,
                      "diverge_process_index": 0,
                      "diverge_scale": 1e-3}}))
        clean = {"total": 2.0}
        assert monkey.maybe_perturb_losses(clean, 2) == clean
        out = monkey.maybe_perturb_losses(clean, 3)
        assert out["total"] != clean["total"]
        # one-shot: the same step never fires twice
        assert monkey.maybe_perturb_losses(clean, 3) == clean

    def test_null_chaos_passthrough(self):
        losses = {"total": 1.0}
        assert chaos._NULL.maybe_perturb_losses(losses, 1) is losses


class TestSinksLogdirFallback:
    def test_no_logdir_routes_away_from_cwd(self, monkeypatch):
        from imaginaire_tpu.telemetry import sinks as sinks_mod

        monkeypatch.setattr(sinks_mod, "_WARNED_NO_LOGDIR", False)
        built = sinks_mod.make_sinks(["jsonl"], logdir=None)
        assert len(built) == 1
        path = built[0].path
        assert os.path.dirname(path) != ""  # never bare-cwd
        assert os.path.normpath(path).startswith("logs" + os.sep)
        assert path.endswith("telemetry.jsonl")

    def test_explicit_logdir_unchanged(self, tmp_path):
        from imaginaire_tpu.telemetry import sinks as sinks_mod

        built = sinks_mod.make_sinks(["jsonl"], logdir=str(tmp_path))
        assert built[0].path == os.path.join(str(tmp_path),
                                             "telemetry.jsonl")

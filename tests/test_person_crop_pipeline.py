"""End-to-end dataset-pipeline coverage for the pose/person-crop and
unprojection data paths the full-scale configs use
(configs/projects/fs_vid2vid/YouTubeDancing/bf16.yaml,
wc_vid2vid/mannequin/hed_bf16.yaml):

- crop_person_from_data as a real ``full_data_ops`` entry: runs at the
  per-type stage of data/base.py::process_item, crops every modality to
  the DensePose person bbox and consumes the instance maps;
- an ``ext: pkl`` unprojections type flows through augmentation
  untouched, is decoded by its convert:: op, and survives the per-type
  loop as a structured payload.
"""

import json
import os
import pickle

import numpy as np
import pytest

from imaginaire_tpu.config import Config
from imaginaire_tpu.registry import resolve

cv2 = pytest.importorskip("cv2")


def _write_pose_fixture(root, t=3, h=96, w=128):
    """images + densepose pose maps + openpose json + instance maps."""
    for dtype in ("images", "pose_maps-densepose", "poses-openpose",
                  "human_instance_maps"):
        os.makedirs(os.path.join(root, dtype, "seq0"), exist_ok=True)
    rng = np.random.RandomState(0)
    for i in range(t):
        img = rng.randint(0, 255, (h, w, 3), np.uint8)
        cv2.imwrite(os.path.join(root, "images", "seq0", f"{i:05d}.jpg"), img)
        dp = np.zeros((h, w, 3), np.uint8)
        dp[30:70, 40:80] = 120  # the person's densepose support
        cv2.imwrite(os.path.join(root, "pose_maps-densepose", "seq0",
                                 f"{i:05d}.png"), dp)
        inst = np.zeros((h, w, 3), np.uint8)
        inst[30:70, 40:80, 2] = 1  # instance id 1 (BGR write -> R channel)
        cv2.imwrite(os.path.join(root, "human_instance_maps", "seq0",
                                 f"{i:05d}.png"), inst)
        joints = []
        for j in range(25):  # full BODY_25 skeleton inside the person box
            joints += [45.0 + (j % 5) * 7 + i, 32.0 + (j // 5) * 8, 0.9]
        people = {"people": [{"pose_keypoints_2d": joints}]}
        with open(os.path.join(root, "poses-openpose", "seq0",
                               f"{i:05d}.json"), "w") as f:
            json.dump(people, f)


def _pose_cfg(root):
    cfg = Config()
    cfg.data = {
        "name": "person_crop_test",
        "type": "imaginaire_tpu.data.paired_videos",
        "num_frames_G": 3,
        "num_frames_D": 3,
        "num_workers": 0,
        "for_pose_dataset": {"pose_type": "both",
                             "remove_face_labels": False,
                             "basic_points_only": False,
                             "random_drop_prob": 0.0},
        "input_types": [
            {"images": {"ext": "jpg", "num_channels": 3,
                        "interpolator": "BILINEAR", "normalize": True}},
            {"pose_maps-densepose": {"ext": "png", "num_channels": 3,
                                     "interpolator": "NEAREST",
                                     "normalize": False}},
            {"poses-openpose": {
                "ext": "json", "num_channels": 3,
                "interpolator": "NEAREST", "normalize": False,
                "pre_aug_ops": "decode_json, convert::imaginaire_tpu.utils."
                               "visualization.pose::openpose_to_npy",
                "post_aug_ops": "vis::imaginaire_tpu.utils."
                                "visualization.pose::draw_openpose_npy"}},
            {"human_instance_maps": {"ext": "png", "num_channels": 3,
                                     "interpolator": "NEAREST",
                                     "normalize": False}},
        ],
        "full_data_ops": "imaginaire_tpu.model_utils."
                         "fs_vid2vid::crop_person_from_data",
        "input_image": ["images"],
        "input_labels": ["pose_maps-densepose", "poses-openpose"],
        "keypoint_data_types": ["poses-openpose"],
        "output_h_w": "64, 32",
        "train": {"roots": [root], "batch_size": 1,
                  "initial_sequence_length": 3,
                  "augmentations": {"resize_h_w": "96, 128",
                                    "horizontal_flip": False}},
        "val": {"roots": [root], "batch_size": 1,
                "augmentations": {"resize_h_w": "96, 128",
                                  "horizontal_flip": False}},
    }
    return cfg


class TestPersonCropThroughPipeline:
    def test_item_cropped_to_output_hw(self, tmp_path):
        root = str(tmp_path / "raw")
        _write_pose_fixture(root)
        cfg = _pose_cfg(root)
        ds = resolve(cfg.data.type, "Dataset")(cfg)
        item = ds[0]
        # every modality cropped to output_h_w, instance maps consumed
        assert item["images"].shape == (3, 64, 32, 3)
        assert item["label"].shape == (3, 64, 32, 6)  # densepose+openpose
        assert "human_instance_maps" not in item
        # the densepose support survived the crop (the bbox centered it)
        dp = item["label"][..., :3]
        assert float(np.abs(dp).max()) > 0
        # multi-person keypoint lists are structured, so no flat '_xy'
        # stash exists (only flat keypoint arrays stash; the rendered
        # maps above carry the pose)
        assert "poses-openpose_xy" not in item


class TestUnprojectionsThroughPipeline:
    def test_pkl_type_decodes_to_structured_payload(self, tmp_path):
        root = str(tmp_path / "raw")
        for dtype in ("images", "unprojections"):
            os.makedirs(os.path.join(root, dtype, "seq0"), exist_ok=True)
        rng = np.random.RandomState(0)
        for i in range(3):
            cv2.imwrite(os.path.join(root, "images", "seq0", f"{i:05d}.jpg"),
                        rng.randint(0, 255, (64, 64, 3), np.uint8))
            mapping = {"64x64": [i, i + 1, 7 + i]}  # one (y, x, idx) row
            with open(os.path.join(root, "unprojections", "seq0",
                                   f"{i:05d}.pkl"), "wb") as f:
                f.write(pickle.dumps(mapping))
        cfg = Config()
        cfg.data = {
            "name": "unproj_test",
            "type": "imaginaire_tpu.data.paired_videos",
            "num_frames_G": 3, "num_frames_D": 3, "num_workers": 0,
            "input_types": [
                {"images": {"ext": "jpg", "num_channels": 3,
                            "interpolator": "BILINEAR", "normalize": True}},
                {"unprojections": {
                    "ext": "pkl",
                    "post_aug_ops": "convert::imaginaire_tpu.model_utils."
                                    "wc_vid2vid::decode_unprojections"}},
            ],
            "input_image": ["images"],
            "input_labels": [],
            "train": {"roots": [root], "batch_size": 1,
                      "initial_sequence_length": 3,
                      "augmentations": {"resize_h_w": "64, 64",
                                        "horizontal_flip": False}},
            "val": {"roots": [root], "batch_size": 1,
                    "augmentations": {"resize_h_w": "64, 64",
                                      "horizontal_flip": False}},
        }
        ds = resolve(cfg.data.type, "Dataset")(cfg)
        item = ds[0]
        assert item["images"].shape == (3, 64, 64, 3)
        unproj = item["unprojections"]
        assert isinstance(unproj, dict) and "64x64" in unproj
        arr = unproj["64x64"]
        assert arr.shape == (3, 2, 3)  # 1 row + sentinel per frame
        # the wc trainer consumes exactly this form
        from imaginaire_tpu.trainers.wc_vid2vid import Trainer as WcTrainer

        info = WcTrainer._finest_resolution(unproj)
        assert info.shape == (3, 2, 3)


class TestPersonCropGeometry:
    def test_bbox_clamped_and_xy_consistent(self, tmp_path):
        """A wide person (width-driven bbox branch) must not overrun the
        frame; the keypoint rescale shares the clamped geometry."""
        from imaginaire_tpu.model_utils.fs_vid2vid import crop_person_from_data

        rng = np.random.RandomState(0)
        t, h, w = 1, 64, 256
        dp = [np.zeros((h, w, 3), np.float32) for _ in range(t)]
        dp[0][20:40, 10:250] = 0.8  # arms spread nearly frame-wide
        data = {"pose_maps-densepose": dp,
                "images": [rng.rand(h, w, 3).astype(np.float32)],
                "poses-openpose_xy": np.asarray([[[30.0, 30.0, 0.9]]])}
        out = crop_person_from_data({"output_h_w": "64, 32"}, True, dict(data))
        assert out["images"][0].shape == (64, 32, 3)
        y0, y1, x0, x1 = out["common_attr"]["crop_coords"]
        assert 0 <= y0 < y1 <= h and 0 <= x0 < x1 <= w
        # the keypoint moved into the crop frame under the SAME geometry
        kp = out["poses-openpose_xy"][0, 0]
        assert 0 <= kp[1] <= 64

    def test_train_jitter_seedable(self):
        from imaginaire_tpu.model_utils.fs_vid2vid import crop_person_from_data

        rng = np.random.RandomState(0)
        dp = [np.zeros((64, 64, 3), np.float32)]
        dp[0][20:50, 20:50] = 0.5
        base = {"pose_maps-densepose": dp,
                "images": [rng.rand(64, 64, 3).astype(np.float32)]}
        a = crop_person_from_data({"output_h_w": "32, 32"}, False, dict(base),
                                  rng=np.random.RandomState(7))
        b = crop_person_from_data({"output_h_w": "32, 32"}, False, dict(base),
                                  rng=np.random.RandomState(7))
        np.testing.assert_array_equal(a["images"][0], b["images"][0])

    def test_inference_common_attr_threads_between_windows(self, tmp_path):
        """Later windows of a pinned inference sequence reuse the first
        window's crop bbox via the dataset-threaded common_attr."""
        root = str(tmp_path / "raw")
        _write_pose_fixture(root, t=3)
        cfg = _pose_cfg(root)
        ds = resolve(cfg.data.type, "Dataset")(cfg, is_inference=True)
        ds.set_inference_sequence_idx(0)
        ds[0]
        first = dict(ds._common_attr)
        ds[1]
        assert ds._common_attr == first  # window 2 reused, not recomputed
        ds.set_inference_sequence_idx(0)
        assert ds._common_attr is None  # new sequence -> fresh bbox


class TestDecodeAlignment:
    def test_missing_resolution_keeps_frame_index(self):
        from imaginaire_tpu.model_utils.wc_vid2vid import decode_unprojections

        frames = [pickle.dumps({"8x8": [0, 0, 1], "4x4": [1, 1, 2]}),
                  pickle.dumps({"8x8": [2, 2, 3]}),  # no coarse entry
                  pickle.dumps({"8x8": [3, 3, 4], "4x4": [2, 2, 5]})]
        out = decode_unprojections(frames)
        assert out["8x8"].shape[0] == 3 and out["4x4"].shape[0] == 3
        # frame 1 of the 4x4 stack is an EMPTY mapping, frame 2 kept its
        # own data (no index shift)
        assert out["4x4"][1, -1].tolist() == [0, 0, 0]
        assert out["4x4"][2, 0].tolist() == [2, 2, 5]


class TestFewShotRefIsolation:
    def test_empty_decoded_mapping_is_none(self):
        from imaginaire_tpu.trainers.wc_vid2vid import Trainer as WcTrainer

        assert WcTrainer._finest_resolution({}) is None

    def test_ref_window_does_not_inherit_driving_crop(self, tmp_path):
        """process_item(thread_common_attr=False) neither reads nor
        writes the sequence-level stash (the few-shot ref window's bbox
        is its own, ref: fs_vid2vid.py:242-256)."""
        root = str(tmp_path / "raw")
        _write_pose_fixture(root, t=3)
        cfg = _pose_cfg(root)
        ds = resolve(cfg.data.type, "Dataset")(cfg, is_inference=True)
        ds.set_inference_sequence_idx(0)
        ds[0]
        stashed = dict(ds._common_attr)
        raw = ds.load_item(*ds._item_spec(1)) if hasattr(ds, "_item_spec") \
            else None
        # drive process_item directly with the flag: stash untouched
        if raw is None:
            raw = {t: [np.zeros((96, 128, 3), np.uint8)]
                   for t in ("images", "pose_maps-densepose",
                             "human_instance_maps")}
            raw["poses-openpose"] = [b'{"people": []}']
        ds.process_item({k: list(v) for k, v in raw.items()},
                        thread_common_attr=False)
        assert ds._common_attr == stashed


class TestResolutionSelection:
    def test_target_hw_beats_finest(self):
        from imaginaire_tpu.trainers.wc_vid2vid import Trainer as WcTrainer

        m = {"256x512": "fine", "64x128": "match"}
        assert WcTrainer._finest_resolution(m, (64, 128)) == "match"
        assert WcTrainer._finest_resolution(m) == "fine"
        assert WcTrainer._finest_resolution(m, (1, 1)) == "fine"  # fallback

    def test_nearest_interp_preserves_discrete_labels(self):
        from imaginaire_tpu.model_utils.fs_vid2vid import crop_and_resize

        f = np.zeros((1, 32, 32, 3), np.float32)
        f[0, :16] = 7.0
        (near,) = crop_and_resize([f], [0, 32, 0, 32], (48, 48),
                                  method="nearest")
        assert set(np.unique(near)) <= {0.0, 7.0}  # no blended values
        (lin,) = crop_and_resize([f], [0, 32, 0, 32], (48, 48))
        assert len(np.unique(lin)) > 2  # bilinear blends the boundary


class TestFirstWindowBarrier:
    def test_prefetch_workers_share_frame0_bbox(self, tmp_path):
        """With num_workers>1 every frame of a pinned sequence must use
        frame 0's crop bbox: workers block on the first-frame barrier
        until frame 0 stashes it (data/paired_videos.py::
        _await_first_frame). The densepose support shifts per frame, so
        an independently computed bbox would differ."""
        import imaginaire_tpu.model_utils.fs_vid2vid as fsu
        from imaginaire_tpu.data.loader import DataLoader

        root = str(tmp_path / "raw")
        t = 8
        for dtype in ("images", "pose_maps-densepose"):
            os.makedirs(os.path.join(root, dtype, "seq0"), exist_ok=True)
        rng = np.random.RandomState(0)
        for i in range(t):
            img = rng.randint(0, 255, (96, 128, 3), np.uint8)
            cv2.imwrite(os.path.join(root, "images", "seq0",
                                     f"{i:05d}.jpg"), img)
            dp = np.zeros((96, 128, 3), np.uint8)
            dp[20 + 3 * i:60 + 3 * i, 30 + 4 * i:70 + 4 * i] = 120
            cv2.imwrite(os.path.join(root, "pose_maps-densepose", "seq0",
                                     f"{i:05d}.png"), dp)
        cfg = _pose_cfg(root)
        # trim to the two modalities this fixture writes
        cfg.data.input_types = [it for it in cfg.data.input_types
                                if list(it)[0] in ("images",
                                                   "pose_maps-densepose")]
        cfg.data.input_labels = ["pose_maps-densepose"]
        cfg.data.keypoint_data_types = []

        used_coords = []
        orig = fsu.crop_person_from_data
        record_lock = __import__("threading").Lock()

        def recording(cfg_, is_inference, data, rng=None):
            # frame 0 (densepose support starting at row 20) is made slow
            # so without the barrier later frames would outrun its stash
            dp0 = np.asarray(data["pose_maps-densepose"][0])
            if int(np.nonzero(dp0.sum((1, 2)))[0][0]) == 20:
                __import__("time").sleep(0.5)
            out = orig(cfg_, is_inference, data, rng=rng)
            with record_lock:
                used_coords.append(tuple(out["common_attr"]["crop_coords"]))
            return out

        fsu.crop_person_from_data = recording
        try:
            ds = resolve(cfg.data.type, "Dataset")(cfg, is_inference=True)
            ds.set_inference_sequence_idx(0)
            # batch_size>1 makes the pool process a window's frames
            # concurrently — the racy case (batch-1 pinned loaders are
            # sequential by construction)
            loader = DataLoader(ds, batch_size=4, shuffle=False,
                                drop_last=False, num_workers=4,
                                prefetch_batches=2,
                                shard_by_process=False)
            n = sum(1 for _ in loader)
        finally:
            fsu.crop_person_from_data = orig
        assert n == 2 and len(used_coords) == t
        assert len(set(used_coords)) == 1, \
            f"every frame must reuse frame 0's bbox, got {set(used_coords)}"

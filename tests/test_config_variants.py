"""The reference ships 12 unit-test configs (scripts/test_training.sh);
these cover the variant configs not exercised by the main per-algorithm
tests: munit_patch (patch-wise D), coco_funit (usb generator),
fs_vid2vid_pose (pose labels + region Ds)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu.config import Config, cfg_get
from imaginaire_tpu.registry import resolve

HERE = os.path.dirname(__file__)
CFGS = os.path.join(HERE, "..", "configs", "unit_test")


def _unpaired_batch(rng, h=64, w=64):
    def img():
        return jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32) * 2 - 1)

    return {"images_a": img(), "images_b": img()}


@pytest.mark.slow
def test_munit_patch_two_iterations(rng, tmp_path):
    cfg = Config(os.path.join(CFGS, "munit_patch.yaml"))
    cfg.logdir = str(tmp_path)
    assert cfg.dis.patch_wise is True
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    batch = _unpaired_batch(rng)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    for it in range(1, 3):
        b = trainer.start_of_iteration(batch, it)
        trainer.dis_update(b)
        g = trainer.gen_update(b)
    for name, v in g.items():
        assert np.isfinite(float(jax.device_get(v))), name


@pytest.mark.slow
def test_coco_funit_two_iterations(rng, tmp_path):
    cfg = Config(os.path.join(CFGS, "coco_funit.yaml"))
    cfg.logdir = str(tmp_path)
    assert cfg.gen.type.endswith("coco_funit")
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    batch = {
        "images_content": jnp.asarray(
            rng.rand(1, 64, 64, 3).astype(np.float32) * 2 - 1),
        "labels_content": jnp.asarray([0]),
        "images_style": jnp.asarray(
            rng.rand(1, 64, 64, 3).astype(np.float32) * 2 - 1),
        "labels_style": jnp.asarray([1]),
    }
    trainer.init_state(jax.random.PRNGKey(0), batch)
    for it in range(1, 3):
        b = trainer.start_of_iteration(batch, it)
        trainer.dis_update(b)
        g = trainer.gen_update(b)
    for name, v in g.items():
        assert np.isfinite(float(jax.device_get(v))), name


def test_fs_vid2vid_pose_dataset():
    cfg = Config(os.path.join(CFGS, "fs_vid2vid_pose.yaml"))
    ds = resolve(cfg.data.type, "Dataset")(cfg)
    item = ds[0]
    assert item["images"].shape == (2, 64, 64, 3)
    assert item["label"].shape == (2, 64, 64, 27)
    assert item["ref_images"].shape[1:] == (64, 64, 3)
    assert item["ref_labels"].shape[1:] == (64, 64, 27)


@pytest.mark.slow
def test_fs_vid2vid_pose_two_iterations(tmp_path):
    cfg = Config(os.path.join(CFGS, "fs_vid2vid_pose.yaml"))
    cfg.logdir = str(tmp_path)
    ds = resolve(cfg.data.type, "Dataset")(cfg)
    item = ds[0]
    batch = {k: jnp.asarray(v)[None] for k, v in item.items()
             if isinstance(v, np.ndarray) and v.ndim >= 3}
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    for it in range(1, 3):
        b = trainer.start_of_iteration(batch, it)
        trainer.dis_update(b)
        g = trainer.gen_update(b)
    for name, v in g.items():
        assert np.isfinite(float(jax.device_get(v))), name
    assert "GAN_face" in g and "GAN_hand" in g


@pytest.mark.slow
def test_fs_vid2vid_inference_finetune(tmp_path):
    """Few-shot inference-time finetune (ref: trainers/fs_vid2vid.py:
    264-292): masked G updates on rolled reference frames; only the
    weight-generator/up/conv_img params move."""
    cfg = Config(os.path.join(CFGS, "fs_vid2vid.yaml"))
    cfg.logdir = str(tmp_path)
    rng = np.random.RandomState(0)

    def img(k=1):
        return jnp.asarray(rng.rand(1, k, 32, 32, 3).astype(np.float32)
                           * 2 - 1)

    batch = {"images": img(2),
             "label": jnp.asarray((rng.rand(1, 2, 32, 32, 13) > 0.9)
                                  .astype(np.float32)),
             "ref_images": img(1),
             "ref_labels": jnp.asarray((rng.rand(1, 1, 32, 32, 13) > 0.9)
                                       .astype(np.float32))}
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    before = jax.tree_util.tree_map(
        lambda x: np.array(x), trainer.state["vars_G"]["params"])
    trainer.finetune(batch, {"finetune_iter": 1})
    assert trainer.has_finetuned
    after = trainer.state["vars_G"]["params"]
    flat_b = jax.tree_util.tree_leaves_with_path(before)
    flat_a = dict(jax.tree_util.tree_leaves_with_path(after))
    moved = frozen = 0
    for path, b in flat_b:
        a = flat_a[path]
        names = [str(p.key) for p in path if hasattr(p, "key")]
        masked_in = any(n.startswith(pref) for n in names
                        for pref in ("weight_generator", "conv_img", "up"))
        changed = not np.allclose(np.asarray(a), b)
        if masked_in:
            moved += changed
        else:
            assert not changed, f"frozen param moved: {names}"
            frozen += 1
    assert moved > 0 and frozen > 0


# ---------------------------------------------------------------------------
# Every shipped full-scale project config must construct its trainer and
# survive one tiny training step (VERDICT r2 #6; the reference's
# equivalent contract is scripts/test_training.sh over unit configs).
# Full-scale channel widths are kept; only the spatial size is shrunk.
# ---------------------------------------------------------------------------

PROJECTS = os.path.join(HERE, "..", "configs", "projects")
PROJECT_CFGS = sorted(
    os.path.relpath(os.path.join(dp, f), PROJECTS)
    for dp, _, fs in os.walk(PROJECTS) for f in fs if f.endswith(".yaml"))


def _label_channels(cfg):
    from imaginaire_tpu.utils.data import get_paired_input_label_channel_number

    return get_paired_input_label_channel_number(cfg.data)


def _project_batch(cfg, rng):
    """Synthetic tiny batch matching the config's trainer family."""
    t = str(cfg.trainer.type)

    def img(*shape):
        return jnp.asarray(rng.rand(*shape, 3).astype(np.float32) * 2 - 1)

    if t.endswith("funit"):  # funit + coco_funit (before the unit check:
        # 'funit'.endswith('unit') is also True)
        return {"images_content": img(1, 64, 64),
                "images_style": img(1, 64, 64),
                "labels_content": jnp.asarray([0], jnp.int32),
                "labels_style": jnp.asarray([1], jnp.int32)}
    if t.endswith(("munit", "unit")):
        # 256px (the configs' real crop): munit's 6 stride-2 residual
        # blocks plus the kernel-4 VALID aggregation underflow below that
        return {"images_a": img(1, 256, 256), "images_b": img(1, 256, 256)}
    n = _label_channels(cfg)
    if t.endswith("fs_vid2vid"):
        label = (rng.rand(1, 64, 64, n) > 0.9).astype(np.float32)
        return {"images": img(1, 2, 64, 64),
                "label": jnp.asarray(label[:, None].repeat(2, 1)),
                "ref_images": img(1, 1, 64, 64),
                "ref_labels": jnp.asarray(label[:, None])}
    if t.endswith("vid2vid"):  # vid2vid + wc_vid2vid at the 128px minimum
        label = (rng.rand(1, 128, 128, n) > 0.9).astype(np.float32)
        return {"images": img(1, 3, 128, 128),
                "label": jnp.asarray(label[:, None].repeat(3, 1))}
    # image family: the full-scale patch-D stacks (5 stride-2 layers on a
    # half-res second scale) collapse to empty outputs below 128px — the
    # reference torch Conv2d would hard-error at the same size
    label = (rng.rand(1, 128, 128, n) > 0.9).astype(np.float32)
    return {"images": img(1, 128, 128), "label": jnp.asarray(label)}


def _build_project_trainer(rel, tmp_path):
    cfg = Config(os.path.join(PROJECTS, rel))
    cfg.logdir = str(tmp_path)
    # no pretrained weights in CI: random-init the perceptual/flow
    # teachers (cost-equivalent; numerics are covered by the goldens)
    if cfg_get(cfg.trainer, "perceptual_loss", None) is not None:
        cfg.trainer.perceptual_loss.allow_random_init = True
        cfg.trainer.perceptual_loss.pop("weights_path", None)
    if cfg_get(cfg, "flow_network", None) is not None:
        cfg.flow_network.allow_random_init = True
        cfg.flow_network.pop("weights_path", None)
    t = str(cfg.trainer.type)
    if t.endswith("vid2vid") and not t.endswith("fs_vid2vid"):
        # the vid2vid/wc generators statically size their bottleneck from
        # the config crop (crop // 2^num_layers, num_layers=7) — shrink
        # the crop to the 128px architecture minimum so the tiny step
        # matches the generator's static shapes
        # the generator bottleneck sizes itself from the VAL augmentations
        # (models/generators/vid2vid.py:122-131), the batch matches train
        _shrink_crops(cfg)
    sim = cfg_get(cfg.gen, "single_image_model", None)
    if sim is not None:
        # no trained single-image checkpoint in CI: random weights, and
        # the frozen SPADE must emit frames at the shrunk 128px crop —
        # write a crop-patched copy of its config
        sim.allow_random_init = True
        sim.pop("checkpoint", None)
        single = Config(sim.config if os.path.exists(sim.config)
                        else os.path.join(HERE, "..", sim.config))
        _shrink_crops(single)
        patched = os.path.join(str(tmp_path), "single_image_model.yaml")
        with open(patched, "w") as f:
            f.write(single.yaml())
        sim.config = patched
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    if sim is not None and getattr(trainer, "single_image_model",
                                   None) is not None:
        # SPADE's minimum output side is 256; the shrunk 128px step can't
        # run the real frozen model, so stub the jitted apply (shape- and
        # gating-faithful; the real 256px takeover apply is covered by
        # tests/test_wc_vid2vid.py::TestSingleImageModel)
        trainer.single_image_vars = {}
        trainer._jit_single = lambda v, d, k: {
            "fake_images": jnp.zeros(d["label"].shape[:3] + (3,),
                                     d["label"].dtype) + 0.1}
    return cfg, trainer


def _shrink_crops(cfg):
    for split in ("train", "val"):
        aug = cfg_get(cfg.data, split, None)
        aug = cfg_get(aug, "augmentations", None) if aug else None
        if aug is None:
            continue
        for key in ("random_crop_h_w", "resize_h_w", "center_crop_h_w"):
            if cfg_get(aug, key, None) is not None:
                aug[key] = "128, 128"
        aug.pop("resize_smallest_side", None)


@pytest.mark.parametrize("rel", PROJECT_CFGS)
def test_project_config_constructs(rel, rng, tmp_path):
    """Every shipped full-scale config parses and builds its trainer
    (models, optimizers, losses) and a family batch synthesizes."""
    cfg, trainer = _build_project_trainer(rel, tmp_path)
    batch = _project_batch(cfg, rng)
    assert trainer.net_G is not None
    assert set(batch)


def _step_one(rel, rng, tmp_path):
    cfg, trainer = _build_project_trainer(rel, tmp_path)
    batch = _project_batch(cfg, rng)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    batch = trainer.start_of_iteration(batch, 1)
    trainer.dis_update(batch)
    g = trainer.gen_update(batch)
    for name, v in g.items():
        assert np.isfinite(float(jax.device_get(v))), (rel, name)


# full-width step representatives: the configs whose training paths are
# NOT already stepped by the per-family unit-config tests — the
# ring-capable spade-attention variant and the three video configs with
# new modalities (pose person-crop, hed guidance). The image families'
# paths run 2-iteration unit configs in their own test files; their
# full-width steps live in the opt-in projects_full sweep.
FAMILY_REPS = [
    "spade/cocostuff/base128_bs4_attn.yaml",
    "vid2vid/dancing/bf16.yaml",
    "fs_vid2vid/YouTubeDancing/bf16.yaml",
    "wc_vid2vid/mannequin/hed_bf16.yaml",
]


@pytest.mark.slow
@pytest.mark.parametrize("rel", FAMILY_REPS)
def test_project_family_rep_steps(rel, rng, tmp_path):
    """One tiny full-width training step per trainer family (spatial
    size shrunk, channel budget kept)."""
    _step_one(rel, rng, tmp_path)


@pytest.mark.projects_full
@pytest.mark.parametrize("rel", [c for c in PROJECT_CFGS
                                 if c not in FAMILY_REPS])
def test_project_config_steps_full(rel, rng, tmp_path):
    """Exhaustive per-config step sweep — hours of single-core CPU, so
    opt-in: ``pytest -m projects_full tests/test_config_variants.py``."""
    _step_one(rel, rng, tmp_path)

"""The reference ships 12 unit-test configs (scripts/test_training.sh);
these cover the variant configs not exercised by the main per-algorithm
tests: munit_patch (patch-wise D), coco_funit (usb generator),
fs_vid2vid_pose (pose labels + region Ds)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu.config import Config
from imaginaire_tpu.registry import resolve

HERE = os.path.dirname(__file__)
CFGS = os.path.join(HERE, "..", "configs", "unit_test")


def _unpaired_batch(rng, h=64, w=64):
    def img():
        return jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32) * 2 - 1)

    return {"images_a": img(), "images_b": img()}


@pytest.mark.slow
def test_munit_patch_two_iterations(rng, tmp_path):
    cfg = Config(os.path.join(CFGS, "munit_patch.yaml"))
    cfg.logdir = str(tmp_path)
    assert cfg.dis.patch_wise is True
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    batch = _unpaired_batch(rng)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    for it in range(1, 3):
        b = trainer.start_of_iteration(batch, it)
        trainer.dis_update(b)
        g = trainer.gen_update(b)
    for name, v in g.items():
        assert np.isfinite(float(jax.device_get(v))), name


@pytest.mark.slow
def test_coco_funit_two_iterations(rng, tmp_path):
    cfg = Config(os.path.join(CFGS, "coco_funit.yaml"))
    cfg.logdir = str(tmp_path)
    assert cfg.gen.type.endswith("coco_funit")
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    batch = {
        "images_content": jnp.asarray(
            rng.rand(1, 64, 64, 3).astype(np.float32) * 2 - 1),
        "labels_content": jnp.asarray([0]),
        "images_style": jnp.asarray(
            rng.rand(1, 64, 64, 3).astype(np.float32) * 2 - 1),
        "labels_style": jnp.asarray([1]),
    }
    trainer.init_state(jax.random.PRNGKey(0), batch)
    for it in range(1, 3):
        b = trainer.start_of_iteration(batch, it)
        trainer.dis_update(b)
        g = trainer.gen_update(b)
    for name, v in g.items():
        assert np.isfinite(float(jax.device_get(v))), name


def test_fs_vid2vid_pose_dataset():
    cfg = Config(os.path.join(CFGS, "fs_vid2vid_pose.yaml"))
    ds = resolve(cfg.data.type, "Dataset")(cfg)
    item = ds[0]
    assert item["images"].shape == (2, 64, 64, 3)
    assert item["label"].shape == (2, 64, 64, 27)
    assert item["ref_images"].shape[1:] == (64, 64, 3)
    assert item["ref_labels"].shape[1:] == (64, 64, 27)


@pytest.mark.slow
def test_fs_vid2vid_pose_two_iterations(tmp_path):
    cfg = Config(os.path.join(CFGS, "fs_vid2vid_pose.yaml"))
    cfg.logdir = str(tmp_path)
    ds = resolve(cfg.data.type, "Dataset")(cfg)
    item = ds[0]
    batch = {k: jnp.asarray(v)[None] for k, v in item.items()
             if isinstance(v, np.ndarray) and v.ndim >= 3}
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    for it in range(1, 3):
        b = trainer.start_of_iteration(batch, it)
        trainer.dis_update(b)
        g = trainer.gen_update(b)
    for name, v in g.items():
        assert np.isfinite(float(jax.device_get(v))), name
    assert "GAN_face" in g and "GAN_hand" in g


@pytest.mark.slow
def test_fs_vid2vid_inference_finetune(tmp_path):
    """Few-shot inference-time finetune (ref: trainers/fs_vid2vid.py:
    264-292): masked G updates on rolled reference frames; only the
    weight-generator/up/conv_img params move."""
    cfg = Config(os.path.join(CFGS, "fs_vid2vid.yaml"))
    cfg.logdir = str(tmp_path)
    rng = np.random.RandomState(0)

    def img(k=1):
        return jnp.asarray(rng.rand(1, k, 32, 32, 3).astype(np.float32)
                           * 2 - 1)

    batch = {"images": img(2),
             "label": jnp.asarray((rng.rand(1, 2, 32, 32, 13) > 0.9)
                                  .astype(np.float32)),
             "ref_images": img(1),
             "ref_labels": jnp.asarray((rng.rand(1, 1, 32, 32, 13) > 0.9)
                                       .astype(np.float32))}
    trainer = resolve(cfg.trainer.type, "Trainer")(cfg)
    trainer.init_state(jax.random.PRNGKey(0), batch)
    before = jax.tree_util.tree_map(
        lambda x: np.array(x), trainer.state["vars_G"]["params"])
    trainer.finetune(batch, {"finetune_iter": 1})
    assert trainer.has_finetuned
    after = trainer.state["vars_G"]["params"]
    flat_b = jax.tree_util.tree_leaves_with_path(before)
    flat_a = dict(jax.tree_util.tree_leaves_with_path(after))
    moved = frozen = 0
    for path, b in flat_b:
        a = flat_a[path]
        names = [str(p.key) for p in path if hasattr(p, "key")]
        masked_in = any(n.startswith(pref) for n in names
                        for pref in ("weight_generator", "conv_img", "up"))
        changed = not np.allclose(np.asarray(a), b)
        if masked_in:
            moved += changed
        else:
            assert not changed, f"frozen param moved: {names}"
            frozen += 1
    assert moved > 0 and frozen > 0

"""Regression: the ops package's function exports shadow its submodules
(ISSUE 19 satellite; this bit the memory autotuner). ``<op>_mod``
aliases are the canonical module handles."""

import importlib
import inspect

import pytest

OPS = ("resample2d", "channelnorm", "correlation", "spade_modulation")


def test_function_import_shadows_submodule():
    """The historical trap, pinned so nobody 'fixes' the docs away:
    the package attribute named after the op IS the function."""
    import imaginaire_tpu.ops as ops

    for op in OPS:
        assert inspect.isfunction(getattr(ops, op)), op


@pytest.mark.parametrize("op", OPS)
def test_mod_alias_is_the_submodule(op):
    import imaginaire_tpu.ops as ops

    alias = getattr(ops, f"{op}_mod")
    assert inspect.ismodule(alias), f"{op}_mod is not a module"
    assert alias is importlib.import_module(f"imaginaire_tpu.ops.{op}")
    # the attribute the autotuner needed when the shadowing bit it
    assert isinstance(alias.AUTO_IMPLEMENTATION, str)
    # and the function the alias carries is the exported one
    assert getattr(alias, op) is getattr(ops, op)


def test_op_modules_table_matches_aliases():
    import imaginaire_tpu.ops as ops

    assert set(ops.OP_MODULES) == set(OPS)
    for op, mod in ops.OP_MODULES.items():
        assert mod is getattr(ops, f"{op}_mod")


def test_resolved_implementations_uses_modules():
    from imaginaire_tpu.ops import OP_MODULES, resolved_implementations

    resolved = resolved_implementations()
    assert set(resolved) == set(OPS)
    for op, impl in resolved.items():
        assert impl == OP_MODULES[op].AUTO_IMPLEMENTATION

"""FlowNet2 port: parameter-count parity, forward shapes, wrapper
confidence, and checkpoint-converter name-mapping round trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from imaginaire_tpu.flow import FlowNet, FlowNet2


def tree_paths(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        p = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(tree_paths(v, p))
        else:
            out[p] = v.shape
    return out


@pytest.fixture(scope="module")
def fn2_variables():
    m = FlowNet2()
    x = jnp.zeros((1, 2, 64, 64, 3), jnp.float32)
    return jax.jit(lambda: m.init(jax.random.PRNGKey(0), x))()


class TestFlowNet2:
    def test_param_count_matches_reference(self, fn2_variables):
        """The reference documents 'Parameter count = 162,518,834'
        (ref: flownet2/models.py:17)."""
        n = sum(p.size for p in jax.tree_util.tree_leaves(fn2_variables))
        assert n == 162_518_834

    def test_forward_shape_and_finite(self, fn2_variables):
        m = FlowNet2()
        x = jnp.asarray(np.random.RandomState(0).rand(1, 2, 64, 64, 3),
                        jnp.float32)
        flow = jax.jit(lambda v, x: m.apply(v, x))(fn2_variables, x)
        assert flow.shape == (1, 64, 64, 2)
        assert np.all(np.isfinite(np.asarray(flow)))

    def test_wrapper_flow_and_conf(self, tmp_path):
        fn = FlowNet(weights_path=str(tmp_path / "none.npz"),
                     allow_random_init=True)
        rng = np.random.RandomState(1)
        a = jnp.asarray(rng.rand(1, 64, 64, 3), jnp.float32)
        b = jnp.asarray(rng.rand(1, 64, 64, 3), jnp.float32)
        flow, conf = fn(a, b)
        assert flow.shape == (1, 64, 64, 2)
        assert conf.shape == (1, 64, 64, 1)
        assert set(np.unique(np.asarray(conf))) <= {0.0, 1.0}
        # identical images at zero flow would be fully confident; random
        # init just needs to produce a valid map
        # 5-D input reshapes through
        a5 = jnp.tile(a[:, None], (1, 2, 1, 1, 1))
        flow5, conf5 = fn(a5, a5)
        assert flow5.shape == (1, 2, 64, 64, 2)
        assert conf5.shape == (1, 2, 64, 64, 1)

    def test_wrapper_resizes_non64(self, tmp_path):
        fn = FlowNet(weights_path=str(tmp_path / "none.npz"),
                     allow_random_init=True)
        rng = np.random.RandomState(1)
        a = jnp.asarray(rng.rand(1, 70, 100, 3), jnp.float32)
        flow, conf = fn(a, a)
        assert flow.shape == (1, 70, 100, 2)
        assert conf.shape == (1, 70, 100, 1)

    def test_converter_name_mapping_bijection(self, fn2_variables, tmp_path):
        """Synthesize a torch state dict from the known reference names,
        convert, and require exact path+shape agreement with the Flax
        tree — proving the converter covers every parameter."""
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        import convert_weights

        flax_paths = tree_paths(fn2_variables["params"])

        # invert: construct the torch key for each flax path
        cs_inv = {"refine5": ("predict_flow6", "upsampled_flow6_to_5",
                              "deconv5"),
                  "refine4": ("predict_flow5", "upsampled_flow5_to_4",
                              "deconv4"),
                  "refine3": ("predict_flow4", "upsampled_flow4_to_3",
                              "deconv3"),
                  "refine2": ("predict_flow3", "upsampled_flow3_to_2",
                              "deconv2")}
        sd_inv = {"refine4": ("inter_conv5", "predict_flow5",
                              "upsampled_flow5_to_4", "deconv4"),
                  "refine3": ("inter_conv4", "predict_flow4",
                              "upsampled_flow4_to_3", "deconv3"),
                  "refine2": ("inter_conv3", "predict_flow3",
                              "upsampled_flow3_to_2", "deconv2")}
        fusion_inv = {"upflow2": "upsampled_flow2_to_1",
                      "upflow1": "upsampled_flow1_to_0"}

        class FakeTensor:
            def __init__(self, arr):
                self._a = arr

            def numpy(self):
                return self._a

        state = {}
        for path, shape in flax_paths.items():
            parts = path.split("/")
            net = parts[0]
            is_kernel = parts[-1] == "kernel"
            is_deconv = "upflow" in path or "/deconv" in path
            if net in ("flownetc", "flownets_1", "flownets_2"):
                if parts[1] in cs_inv:
                    pf, uf, dc = cs_inv[parts[1]]
                    tname = {"predict": pf, "upflow": uf, "deconv": dc}[
                        parts[2]]
                else:
                    tname = parts[1]
            elif net == "flownets_d":
                if parts[1] in sd_inv:
                    ic, pf, uf, dc = sd_inv[parts[1]]
                    tname = {"inter": ic, "predict": pf, "upflow": uf,
                             "deconv": dc}[parts[2]]
                elif parts[1] == "upflow6":
                    tname = "upsampled_flow6_to_5"
                else:
                    tname = parts[1]
            else:  # fusion
                tname = fusion_inv.get(parts[1], parts[1])
            suffix = "weight" if is_kernel else "bias"
            seq = "" if ("upsampled" in tname) else ".0"
            if tname.startswith("predict_flow") or tname == "deconv5" \
                    and net == "flownets_d":
                pass
            # predict_flow convs are bare (no Sequential) in torch
            if tname.startswith("predict_flow") or "upsampled" in tname:
                key = f"{net}.{tname}.{suffix}"
            else:
                key = f"{net}.{tname}.0.{suffix}"
            if is_kernel:
                kh, kw, a, b = shape
                arr = (np.transpose(np.random.rand(*shape).astype(np.float32),
                                    (2, 3, 0, 1))[:, :, ::-1, ::-1]
                       if is_deconv else
                       np.transpose(np.random.rand(*shape).astype(np.float32),
                                    (3, 2, 0, 1)))
            else:
                arr = np.random.rand(*shape).astype(np.float32)
            state[key] = FakeTensor(arr)

        import torch

        ckpt = tmp_path / "fake_flownet2.pth"
        torch.save({"state_dict": {k: torch.from_numpy(v.numpy().copy())
                                   for k, v in state.items()}}, ckpt)
        out = tmp_path / "flownet2.npz"
        convert_weights.convert_flownet2(str(ckpt), str(out))

        from imaginaire_tpu.flow.flow_net import load_flownet2_npz

        converted = tree_paths(load_flownet2_npz(str(out)))
        assert converted == flax_paths

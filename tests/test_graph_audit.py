"""Graph auditor (ISSUE 12): jaxpr/HLO static analysis + AST lint.

Three layers:

- deliberately-bad toy programs, one per audit rule — each violation
  must NAME its jaxpr path (or donated-arg path), because an
  unlocatable verdict is useless to the person fixing it;
- AST-rule toys incl. the allowlist contract (reasoned allow
  suppresses; a reasonless allow is itself a violation);
- clean passes: every trainer family's real step programs audit to
  zero violations (video families are slow-marked), and the repo's own
  sources pass the lint — the same gates CI runs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from imaginaire_tpu import analysis
from imaginaire_tpu.analysis import (
    ast_rules,
    collectives,
    donation,
    hlo_audit,
    islands,
    jaxpr_audit,
)


def _trace(fn, *args):
    return jax.jit(fn).trace(*args)


def _rules(violations):
    return [v.rule for v in violations]


# ------------------------------------------------------------ jaxpr rules


class TestJaxprRules:
    def test_host_callback_named(self):
        def bad(x):
            jax.debug.print("x={x}", x=x)
            return x * 2

        tr = _trace(bad, jnp.ones((4,)))
        viols, stats = jaxpr_audit.audit_jaxpr("toy", tr.jaxpr)
        assert "host_callback" in _rules(viols)
        v = next(v for v in viols if v.rule == "host_callback")
        assert "eqns[" in v.path, v.path  # names the offending equation
        assert stats["callback_eqns"] >= 1

    def test_pure_callback_named(self):
        def bad(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2,
                jax.ShapeDtypeStruct((4,), np.float32), x)

        tr = _trace(bad, jnp.ones((4,), jnp.float32))
        viols, _ = jaxpr_audit.audit_jaxpr("toy", tr.jaxpr)
        v = next(v for v in viols if v.rule == "host_callback")
        assert "eqns[" in v.path

    def test_f64_leak_named(self):
        from jax.experimental import enable_x64

        with enable_x64():
            def bad(x):
                return jnp.sum(x.astype(jnp.float64))

            tr = _trace(bad, jnp.ones((4,), jnp.float32))
            viols, stats = jaxpr_audit.audit_jaxpr("toy", tr.jaxpr)
        assert "f64_leak" in _rules(viols)
        v = next(v for v in viols if v.rule == "f64_leak")
        assert "eqns[" in v.path
        assert stats["f64_eqns"] >= 1

    def test_island_cast_named(self):
        def bad(x):
            with islands.scope("norm_stats"):
                m = jnp.mean(x.astype(jnp.float32))
                return m.astype(jnp.bfloat16)  # cast INSIDE the island

        tr = _trace(bad, jnp.ones((4, 4), jnp.bfloat16))
        viols, _ = jaxpr_audit.audit_jaxpr("toy", tr.jaxpr)
        assert "island_cast" in _rules(viols)
        v = next(v for v in viols if v.rule == "island_cast")
        assert "eqns[" in v.path
        assert "norm_stats" in v.message

    def test_island_exit_cast_outside_is_clean(self):
        def good(x):
            with islands.scope("norm_stats"):
                m = jnp.mean(x.astype(jnp.float32))
            return m.astype(jnp.bfloat16)  # exit cast OUTSIDE

        tr = _trace(good, jnp.ones((4, 4), jnp.bfloat16))
        viols, _ = jaxpr_audit.audit_jaxpr("toy", tr.jaxpr)
        assert "island_cast" not in _rules(viols)

    def test_unregistered_island_scope_raises(self):
        with pytest.raises(KeyError, match="not registered"):
            with islands.scope("no_such_island"):
                pass

    def test_island_guard(self):
        islands.guard("norm_stats", ok=jnp.ones((2,), jnp.float32))
        with pytest.raises(islands.IslandViolation, match="float32"):
            islands.guard("norm_stats",
                          bad=jnp.ones((2,), jnp.bfloat16))

    def test_baked_constant_named(self):
        big = jnp.asarray(np.ones((256, 256), np.float32))  # 256 KiB

        def bad(x):
            return x + big

        tr = _trace(bad, jnp.ones((256, 256), jnp.float32))
        viols, stats = jaxpr_audit.audit_jaxpr(
            "toy", tr.jaxpr, const_bytes_limit=64 << 10)
        assert "baked_constant" in _rules(viols)
        v = next(v for v in viols if v.rule == "baked_constant")
        assert "f32" in v.message or "float32" in v.message
        assert stats["const_bytes"] >= 256 * 1024

    def test_small_constants_pass(self):
        small = jnp.ones((8,), jnp.float32)

        def good(x):
            return x + small

        tr = _trace(good, jnp.ones((8,), jnp.float32))
        viols, _ = jaxpr_audit.audit_jaxpr("toy", tr.jaxpr,
                                           const_bytes_limit=64 << 10)
        assert not viols


# ---------------------------------------------------- donation + HLO view


class TestDonation:
    def test_dead_donation_named(self):
        def f(a, b, c):
            return a + c  # b is donated but unused

        jitted = jax.jit(f, donate_argnums=(0, 1))
        args = (jnp.ones((8,)), jnp.ones((8,)), jnp.ones((8,)))
        traced = jitted.trace(*args)
        lowered = traced.lower()
        compiled = lowered.compile()
        hlo = compiled.as_text()
        viols, summary = donation.audit_donation(
            "toy", compiled, traced.jaxpr, lowered, hlo)
        assert summary["declared"] == 2
        assert summary["dead_count"] == 1
        v = next(v for v in viols if v.rule == "dead_donation")
        assert "[0][1]" in v.path  # names WHICH donated arg is dead
        assert summary["aliased"] >= 1  # arg a still aliases

    def test_live_donations_clean(self):
        def f(a, b):
            return a + b, a * b

        jitted = jax.jit(f, donate_argnums=(0, 1))
        args = (jnp.ones((8,)), jnp.ones((8,)))
        traced = jitted.trace(*args)
        lowered = traced.lower()
        compiled = lowered.compile()
        viols, summary = donation.audit_donation(
            "toy", compiled, traced.jaxpr, lowered, compiled.as_text())
        assert summary["dead_count"] == 0
        assert not viols

    def test_alias_map_parse(self):
        hlo = ("HloModule jit_f, input_output_alias={ {0}: (0, {}, "
               "may-alias), {1}: (2, {}, must-alias) }\n")
        assert hlo_audit.aliased_param_indices(hlo) == {0, 2}

    def test_collective_stats(self):
        hlo = ("  ar = f32[1024]{0} all-reduce(p), replica_groups={}\n"
               "  ag.1 = bf16[2,64]{1,0} all-gather(x), dimensions={0}\n")
        stats = hlo_audit.collective_stats(hlo)
        assert stats["all-reduce"]["count"] == 1
        assert stats["all-reduce"]["bytes"] == 4096
        assert stats["all-gather"]["bytes"] == 256

    def test_jaxpr_collectives(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("d",))
        from imaginaire_tpu.parallel import shard_map
        from jax.sharding import PartitionSpec

        fn = shard_map(lambda x: jax.lax.psum(x, "d"), mesh=mesh,
                       in_specs=PartitionSpec("d"),
                       out_specs=PartitionSpec())
        tr = _trace(fn, jnp.ones((8, 4)))
        found = collectives.jaxpr_collectives(tr.jaxpr)
        assert "psum" in found


# ------------------------------------------------------------- audit_program


class TestAuditProgram:
    def test_full_report_shape(self):
        def f(a, b):
            return a + 1.0  # b donated-dead

        jitted = jax.jit(f, donate_argnums=(0, 1))
        args = (jnp.ones((4,)), jnp.ones((4,)))
        traced = jitted.trace(*args)
        lowered = traced.lower()
        compiled = lowered.compile()
        audit = analysis.audit_program("toy", traced=traced,
                                       lowered=lowered,
                                       compiled=compiled)
        assert audit["violation_count"] == 1
        assert audit["violations"][0]["rule"] == "dead_donation"
        assert audit["donation"]["dead_count"] == 1
        assert "collectives" in audit
        assert "errors" not in audit or not audit["errors"]

    def test_trace_only(self):
        tr = _trace(lambda x: x * 2, jnp.ones((4,)))
        audit = analysis.audit_program("toy", traced=tr,
                                       include_hlo=False)
        assert audit["violation_count"] == 0


# ---------------------------------------------------------------- AST rules


def _lint(src, rel="imaginaire_tpu/models/toy.py"):
    viols, sups = ast_rules.lint_source(src, rel)
    return [v.rule for v in viols], sups


class TestAstRules:
    def test_bare_jit(self):
        rules, _ = _lint("import jax\nf = jax.jit(lambda x: x)\n")
        assert "bare-jit" in rules

    def test_bare_jit_allowed_in_ledger_home(self):
        rules, _ = _lint("import jax\nf = jax.jit(lambda x: x)\n",
                         rel="imaginaire_tpu/telemetry/xla_obs.py")
        assert "bare-jit" not in rules

    def test_host_sync(self):
        rules, _ = _lint(
            "import jax\n\ndef f(x):\n    return jax.device_get(x)\n",
            rel="imaginaire_tpu/trainers/toy.py")
        assert "host-sync" in rules

    def test_untimed_barrier(self):
        rules, _ = _lint(
            "from jax.experimental import multihost_utils\n"
            "multihost_utils.sync_global_devices('x')\n",
            rel="imaginaire_tpu/trainers/toy.py")
        assert "untimed-barrier" in rules

    def test_numpy_random_in_traced_code(self):
        rules, _ = _lint(
            "import numpy as np\n\ndef f(x):\n"
            "    return x + np.random.rand(4)\n")
        assert "numpy-random" in rules

    def test_mutable_default_pytree(self):
        rules, _ = _lint(
            "from flax import linen as nn\n\n"
            "class M(nn.Module):\n    scales: list = []\n")
        assert "mutable-default-pytree" in rules

    def test_allow_with_reason_suppresses(self):
        rules, sups = _lint(
            "import jax\n"
            "# lint: allow(bare-jit) -- toy reason\n"
            "f = jax.jit(lambda x: x)\n")
        assert "bare-jit" not in rules
        assert sups and sups[0].reason == "toy reason"

    def test_allow_without_reason_is_a_violation(self):
        rules, _ = _lint(
            "import jax\n"
            "# lint: allow(bare-jit)\n"
            "f = jax.jit(lambda x: x)\n")
        assert "allowlist-reason" in rules

    def test_repo_is_lint_clean(self):
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        viols, sups = ast_rules.lint_repo(root)
        assert not viols, [v.as_dict() for v in viols]
        # zero silent suppressions: every allow carries its reason
        assert all(s.reason for s in sups)


# ------------------------------------------------- real-program clean pass


IMAGE_FAMILIES = ("spade", "pix2pixHD", "unit", "munit", "funit",
                  "coco_funit")
VIDEO_FAMILIES = ("vid2vid", "fs_vid2vid", "wc_vid2vid")


def _assert_family_clean(family):
    from imaginaire_tpu.analysis import programs

    audits = programs.audit_family(family)
    assert audits, f"no programs traced for {family}"
    for label, audit in audits.items():
        assert audit.get("violation_count", 0) == 0, \
            f"{family}/{label}: {audit['violations']}"
        assert not audit.get("errors"), \
            f"{family}/{label} audit errored: {audit['errors']}"


@pytest.mark.parametrize("family", IMAGE_FAMILIES)
def test_family_step_programs_clean(family):
    _assert_family_clean(family)


@pytest.mark.slow
@pytest.mark.parametrize("family", VIDEO_FAMILIES)
def test_video_family_step_programs_clean(family):
    _assert_family_clean(family)


def test_aux_programs_clean():
    from imaginaire_tpu.analysis import programs

    for label, traced in programs.trace_aux_programs():
        audit = analysis.audit_program(label, traced=traced,
                                       include_hlo=False)
        assert audit["violation_count"] == 0, \
            f"{label}: {audit['violations']}"

"""2-D (data x model) partition plan + cross-replica sharded update
state (ISSUE 6, parallel/partition.py).

Covers: logical-axis rule resolution over every family's REAL param
tree (via jax.eval_shape — no init compute), sharded-optimizer vs
replicated-optimizer step parity on a virtual 4-device mesh (bit parity
under sgd, fp32 tolerance under adam+EMA over 3 steps), a
zero-recompile assert across 3 steps under the 2-D mesh, the
place_committed_batch 2-D divisibility contract, checkpoint restore
onto a different mesh shape (reshard, ckpt/reshard meta), and the
dead-model-axis warning.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import __graft_entry__ as ge
from imaginaire_tpu.config import AttrDict, Config
from imaginaire_tpu.parallel.mesh import (
    create_mesh,
    mesh_from_config,
    set_mesh,
)
from imaginaire_tpu.parallel.partition import (
    DEFAULT_RULES,
    PartitionPlan,
    leaf_logical_axes,
    leaf_partition_spec,
    per_device_tree_bytes,
    state_bytes_report,
)
from imaginaire_tpu.parallel.sharding import place_committed_batch
from imaginaire_tpu.registry import resolve

CONFIGS = os.path.join(os.path.dirname(__file__), "..", "configs",
                       "unit_test")


def _mesh_2x2():
    return create_mesh(("data", "model"), (2, 2),
                       devices=np.array(jax.devices()[:4]))


def _mesh_4x1():
    return create_mesh(("data", "model"), (4, 1),
                       devices=np.array(jax.devices()[:4]))


def _tiny_trainer(mesh_shape=None, opt=None, model_average=True,
                  min_shard_size=8):
    cfg = ge._tiny_cfg()
    cfg.trainer.model_average = model_average
    cfg.diagnostics.dg_ratio_warn_low = 0.0
    cfg.diagnostics.dg_ratio_warn_high = 1e9
    if opt is not None:
        cfg.gen_opt.type = opt
        cfg.dis_opt.type = opt
    if mesh_shape is not None:
        cfg.parallel.mesh_shape = dict(mesh_shape)
        cfg.parallel.min_shard_size = min_shard_size
    return resolve(cfg.trainer.type, "Trainer")(cfg), cfg


class TestRuleResolution:
    def test_logical_axes(self):
        assert leaf_logical_axes("kernel", (3, 3, 64, 128)) == \
            ("conv_kh", "conv_kw", "conv_in", "conv_out")
        assert leaf_logical_axes("kernel", (64, 128)) == \
            ("dense_in", "dense_out")
        assert leaf_logical_axes("embedding", (10, 16)) == \
            ("embed_vocab", "embed_features")
        assert leaf_logical_axes("bias", (128,)) == ("features",)
        assert leaf_logical_axes("count", ()) == ()
        # vmapped hyper-conv kernels keep leading stack dims replicated
        assert leaf_logical_axes("kernel", (4, 3, 3, 8, 16))[0] == "stack"

    def test_out_channel_preferred_in_channel_fallback(self):
        sizes = {"data": 2, "model": 2}
        # wide out -> model on out
        spec = leaf_partition_spec("kernel", (3, 3, 64, 128), sizes,
                                   min_shard_size=8)
        assert tuple(spec) == (None, None, None, "model")
        # narrow/indivisible out (RGB conv) -> model falls back to in
        spec = leaf_partition_spec("kernel", (3, 3, 64, 3), sizes,
                                   min_shard_size=8)
        assert tuple(spec) == (None, None, "model")
        # below the channel threshold -> replicated
        spec = leaf_partition_spec("kernel", (3, 3, 4, 4), sizes,
                                   min_shard_size=8)
        assert tuple(spec) == ()

    def test_update_axis_on_first_free_dim(self):
        sizes = {"data": 2, "model": 2}
        spec = leaf_partition_spec("kernel", (3, 3, 64, 128), sizes,
                                   min_shard_size=8, update_axis="data")
        assert tuple(spec) == (None, None, "data", "model")
        spec = leaf_partition_spec("bias", (128,), sizes,
                                   min_shard_size=8, update_axis="data")
        assert tuple(spec) == ("data",)
        # scalars (adam count, madam p_max) stay replicated
        spec = leaf_partition_spec("count", (), sizes,
                                   min_shard_size=8, update_axis="data")
        assert tuple(spec) == ()

    # every family's real generator param tree: eval_shape the flax init
    # (no compute), resolve the rules, and demand full coverage — every
    # leaf resolves to a spec, and no wide conv above the channel
    # threshold is left replicated on a live model axis
    FAMILY_DATA = {
        "spade": lambda rng: {
            "images": rng.rand(1, 256, 256, 3).astype(np.float32),
            "label": (rng.rand(1, 256, 256, 14) > 0.9).astype(np.float32)},
        "pix2pixHD": lambda rng: {
            "images": rng.rand(1, 256, 256, 3).astype(np.float32),
            "label": (rng.rand(1, 256, 256, 14) > 0.9).astype(np.float32),
            "instance_maps": rng.rand(1, 256, 256, 1).astype(np.float32)},
        "unit": lambda rng: {
            "images_a": rng.rand(1, 64, 64, 3).astype(np.float32),
            "images_b": rng.rand(1, 64, 64, 3).astype(np.float32)},
        "munit": lambda rng: {
            "images_a": rng.rand(1, 64, 64, 3).astype(np.float32),
            "images_b": rng.rand(1, 64, 64, 3).astype(np.float32)},
        "funit": lambda rng: {
            "images_content": rng.rand(1, 64, 64, 3).astype(np.float32),
            "labels_content": np.asarray([1], np.int32),
            "images_style": rng.rand(1, 64, 64, 3).astype(np.float32),
            "labels_style": np.asarray([1], np.int32)},
    }

    @pytest.mark.parametrize("family", sorted(FAMILY_DATA))
    def test_family_param_tree_coverage(self, family, rng):
        cfg = Config(os.path.join(CONFIGS, f"{family}.yaml"))
        net_G = resolve(cfg.gen.type, "Generator")(cfg.gen, cfg.data)
        data = self.FAMILY_DATA[family](rng)
        shapes = jax.eval_shape(
            lambda d: net_G.init({"params": jax.random.PRNGKey(0),
                                  "noise": jax.random.PRNGKey(1)},
                                 d, training=True), data)
        params = shapes["params"]
        mesh = _mesh_2x2()
        plan = PartitionPlan(
            {"parallel": {"mesh_shape": {"data": 2, "model": 2},
                          "min_shard_size": 16}}, mesh=mesh)
        hits = [0]
        specs = plan.param_specs(params, _model_hits=hits)
        flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_s = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda s: type(s).__name__ == "PartitionSpec")
        # every leaf resolved to a spec
        assert len(flat_p) == len(flat_s)
        wide_unsharded = []
        for (path, leaf), spec in zip(flat_p, flat_s):
            name = str(path[-1].key) if hasattr(path[-1], "key") else ""
            if name == "kernel" and leaf.ndim >= 2:
                widths = [d for d in leaf.shape[-2:]
                          if d >= 16 and d % 2 == 0]
                if widths and "model" not in tuple(spec):
                    wide_unsharded.append(
                        (jax.tree_util.keystr(path), leaf.shape))
        assert not wide_unsharded, \
            f"{family}: wide convs left replicated: {wide_unsharded[:8]}"
        assert hits[0] > 0, f"{family}: no leaf uses the model axis"

    @pytest.mark.parametrize("family,yaml",
                             [("vid2vid", "vid2vid_street.yaml"),
                              ("fs_vid2vid", "fs_vid2vid.yaml")])
    def test_video_family_param_tree_coverage(self, family, yaml, rng):
        """The video generators (flow-warp, hyper-weight) init per
        frame; eval_shape their full init_all tree and demand the same
        rule coverage."""
        from imaginaire_tpu.utils.data import (
            get_paired_input_label_channel_number,
        )

        cfg = Config(os.path.join(CONFIGS, yaml))
        net_G = resolve(cfg.gen.type, "Generator")(cfg.gen, cfg.data)
        n_lab = get_paired_input_label_channel_number(cfg.data)
        data_t = {
            "label": (rng.rand(1, 64, 64, n_lab) > 0.9).astype(np.float32),
            "image": rng.rand(1, 64, 64, 3).astype(np.float32) * 2 - 1,
        }
        if family == "fs_vid2vid":
            data_t["ref_images"] = rng.rand(1, 1, 64, 64, 3).astype(
                np.float32) * 2 - 1
            data_t["ref_labels"] = (rng.rand(1, 1, 64, 64, n_lab) > 0.9
                                    ).astype(np.float32)
        shapes = jax.eval_shape(
            lambda d: net_G.init({"params": jax.random.PRNGKey(0),
                                  "noise": jax.random.PRNGKey(1)},
                                 d, training=True, init_all=True), data_t)
        params = shapes["params"]
        plan = PartitionPlan(
            {"parallel": {"mesh_shape": {"data": 2, "model": 2},
                          "min_shard_size": 16}}, mesh=_mesh_2x2())
        hits = [0]
        specs = plan.param_specs(params, _model_hits=hits)
        assert len(jax.tree_util.tree_leaves(params)) == len(
            jax.tree_util.tree_leaves(
                specs,
                is_leaf=lambda s: type(s).__name__ == "PartitionSpec"))
        assert hits[0] > 0, f"{family}: no leaf uses the model axis"


class TestShardedStepParity:
    """Sharded-optimizer step vs replicated step on the virtual 4-device
    mesh. Under sgd the two are BIT-identical (the update is lr*g, so
    the only differences would be real partitioning bugs). Under adam
    the collective reduction order (reduce-scatter+all-gather vs
    all-reduce) perturbs grads at bit level and the rsqrt normalization
    amplifies that to update scale for near-zero grads — so the
    adam/EMA path asserts fp32-tolerance parity over 3 full steps (the
    acceptance criterion) instead of bit equality."""

    def _one_step(self, mesh, mesh_shape, opt, bs, steps=1):
        set_mesh(mesh)
        trainer, _ = _tiny_trainer(mesh_shape=mesh_shape, opt=opt)
        batch = jax.tree_util.tree_map(
            np.asarray, ge._tiny_batch(bs, h=64, w=64))
        trainer.init_state(jax.random.PRNGKey(0), batch)
        b = place_committed_batch(batch, mesh=mesh)
        hist = []
        for _ in range(steps):
            d = trainer.dis_update(b)
            g = trainer.gen_update(b)
            hist.append((float(d["total"]), float(g["total"])))
        return trainer, hist

    @pytest.mark.slow
    def test_sgd_bit_parity_zero1(self):
        """Pure cross-replica update-state sharding ((4,1): no model
        axis) must reproduce the replicated optimizer step bit for
        bit."""
        mesh = _mesh_4x1()
        t_rep, h_rep = self._one_step(mesh, None, "sgd", 4)
        t_shd, h_shd = self._one_step(
            mesh, {"data": 4, "model": 1}, "sgd", 4)
        assert t_shd.partition.active and not t_rep.partition.active
        assert h_rep == h_shd
        for key in ("vars_G", "vars_D"):
            rep = jax.device_get(t_rep.state[key]["params"])
            shd = jax.device_get(t_shd.state[key]["params"])
            for a, b in zip(jax.tree_util.tree_leaves(rep),
                            jax.tree_util.tree_leaves(shd)):
                np.testing.assert_array_equal(a, b)
        # sgd is stateless (no moments) — the EMA tree is the update
        # state here, and it really is sharded (<1/2 resident per chip)
        report = state_bytes_report(t_shd.state)
        assert report["ema_G"]["per_device_bytes"] < \
            0.5 * report["ema_G"]["global_bytes"]

    @pytest.mark.slow
    def test_adam_ema_three_step_fp32_parity_and_zero_recompiles(self):
        """Full 2-D plan ((2,2): model-sharded convs + data-sharded
        adam moments + EMA): 3-step losses match the replicated run to
        fp32 tolerance, params stay close, and the warm loop holds ONE
        executable per program (zero recompiles)."""
        from imaginaire_tpu.telemetry import xla_obs

        mesh = _mesh_2x2()
        t_rep, h_rep = self._one_step(mesh, None, None, 2, steps=3)
        t_shd, h_shd = self._one_step(
            mesh, {"data": 2, "model": 2}, None, 2, steps=3)
        np.testing.assert_allclose(np.asarray(h_shd), np.asarray(h_rep),
                                   rtol=5e-3)
        rep = jax.device_get(t_rep.state["vars_G"]["params"])
        shd = jax.device_get(t_shd.state["vars_G"]["params"])
        for a, b in zip(jax.tree_util.tree_leaves(rep),
                        jax.tree_util.tree_leaves(shd)):
            np.testing.assert_allclose(a, b, atol=5e-3)
        # zero-recompile contract across the 3 sharded steps: one
        # fingerprint per program, no counted recompiles
        assert t_shd._jit_gen_step._cache_size() == 1
        assert t_shd._jit_dis_step._cache_size() == 1
        assert xla_obs.ledger().recompiles == 0
        # EMA + moments shard over data; params replicate over data but
        # shard wide channels over model
        ema_leaf = jax.tree_util.tree_leaves(t_shd.state["ema_G"])[0]
        assert "data" in jax.tree_util.tree_flatten(
            tuple(ema_leaf.sharding.spec))[0] or \
            tuple(ema_leaf.sharding.spec) != ()
        report = state_bytes_report(t_shd.state)
        for key in ("opt_G", "ema_G"):
            assert report[key]["per_device_bytes"] < \
                0.75 * report[key]["global_bytes"], report


class TestPlaceCommittedBatch2D:
    def test_bs2_commits_sharded_on_2x2(self):
        """Satellite: batch divisibility is judged against the DATA
        axis size (2), not mesh.size (4) — bs2 on a (2,2) mesh must
        commit sharded, not fall back to uncommitted transfer."""
        mesh = _mesh_2x2()
        set_mesh(mesh)
        batch = {"images": np.zeros((2, 8, 8, 3), np.float32)}
        out = place_committed_batch(batch, mesh=mesh)
        spec = out["images"].sharding.spec
        assert tuple(spec)[0] == "data", spec
        assert out["images"].sharding.mesh.shape["model"] == 2

    def test_indivisible_bs_falls_back(self):
        mesh = _mesh_2x2()
        batch = {"images": np.zeros((3, 8, 8, 3), np.float32)}
        out = place_committed_batch(batch, mesh=mesh)
        # bs3 % data(2) != 0 -> uncommitted placement, not a crash
        assert not isinstance(getattr(out["images"], "sharding", None),
                              type(None)) or True
        from jax.sharding import NamedSharding

        sh = out["images"].sharding
        assert not (isinstance(sh, NamedSharding)
                    and tuple(sh.spec)[:1] == ("data",))

    def test_axisless_mesh_replicates(self):
        mesh = create_mesh(("model",), (4,),
                           devices=np.array(jax.devices()[:4]))
        batch = {"images": np.zeros((4, 8, 8, 3), np.float32)}
        out = place_committed_batch(batch, mesh=mesh)  # no 'data' axis
        assert out["images"].shape == (4, 8, 8, 3)


class TestMeshFromConfig:
    def test_parallel_group_wins(self):
        cfg = Config()
        cfg.parallel.mesh_shape = {"data": 2, "model": 2}
        mesh = mesh_from_config(cfg, devices=np.array(jax.devices()[:4]))
        assert dict(mesh.shape) == {"data": 2, "model": 2}

    def test_legacy_runtime_mesh_fallback(self):
        cfg = Config()
        mesh = mesh_from_config(cfg, devices=np.array(jax.devices()))
        assert tuple(mesh.axis_names) == ("data",)
        assert mesh.size == len(jax.devices())

    def test_dead_model_axis_warns(self, caplog):
        """Satellite: a model axis of size >1 that no rule consumes is
        named loudly instead of silently replicating."""
        import logging

        mesh = _mesh_2x2()
        plan = PartitionPlan(
            {"parallel": {"mesh_shape": {"data": 2, "model": 2},
                          # threshold above every leaf width -> no match
                          "min_shard_size": 10_000_000}}, mesh=mesh)
        state = {"vars_G": {"params": {"conv": {
            "kernel": jnp.zeros((3, 3, 16, 32))}}},
            "step": jnp.zeros((), jnp.int32)}
        with caplog.at_level(logging.WARNING,
                             logger="imaginaire_tpu.parallel.partition"):
            plan.state_specs(state)
        assert any("model axis" in r.message for r in caplog.records)
        # and only once
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="imaginaire_tpu.parallel.partition"):
            plan.state_specs(state)
        assert not any("model axis" in r.message for r in caplog.records)

    def test_default_rules_cover_snippets_pattern(self):
        # the DEFAULT_RULES table maps channel-ish axes to 'model' and
        # keeps batch-ish/feature axes unsharded, mirroring the
        # SNIPPETS [2]/[3] pattern
        assert DEFAULT_RULES["conv_out"] == "model"
        assert DEFAULT_RULES["features"] is None
        assert DEFAULT_RULES["embed_vocab"] is None


@pytest.mark.slow
class TestCheckpointReshard:
    def test_restore_onto_different_mesh_reshards(self, tmp_path):
        """Satellite: a checkpoint saved under one mesh shape restores
        under another — resharded via jax.device_put, with a
        ckpt/reshard telemetry meta event — instead of crashing or
        silently replicating."""
        from imaginaire_tpu import telemetry

        mesh = _mesh_2x2()
        set_mesh(mesh)
        trainer, cfg = _tiny_trainer(mesh_shape={"data": 2, "model": 2})
        cfg.logdir = str(tmp_path)
        trainer.cfg.logdir = str(tmp_path)
        batch = jax.tree_util.tree_map(
            np.asarray, ge._tiny_batch(2, h=64, w=64))
        trainer.init_state(jax.random.PRNGKey(0), batch)
        path = trainer.save_checkpoint(0, 1)
        assert os.path.exists(path + ".partition.json")
        saved_desc = json.load(open(path + ".partition.json"))
        assert saved_desc["mesh_shape"] == [2, 2]

        # restore onto a (4,1) mesh (different shape, ZeRO-only plan)
        mesh41 = _mesh_4x1()
        set_mesh(mesh41)
        tdir = str(tmp_path / "telemetry")
        tm = telemetry.configure(logdir=tdir, enabled=True,
                                 sinks=("jsonl",), flush_every_n_steps=1)
        trainer2, cfg2 = _tiny_trainer(mesh_shape={"data": 4, "model": 1})
        trainer2.cfg.logdir = str(tmp_path)
        trainer2.init_state(jax.random.PRNGKey(1), batch)
        assert trainer2.load_checkpoint(path, resume=True)
        # params identical after the mesh change...
        a = jax.device_get(trainer.state["vars_G"]["params"])
        b = jax.device_get(trainer2.state["vars_G"]["params"])
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(x, y)
        # ...and the update state is committed under the NEW plan
        mu = jax.tree_util.tree_leaves(trainer2.state["opt_G"])[1]
        assert mu.sharding.mesh.shape["data"] == 4
        tm.shutdown()
        events = [json.loads(line) for line in
                  open(os.path.join(tdir, "telemetry.jsonl"))]
        reshard = [e for e in events
                   if e.get("kind") == "meta"
                   and e.get("name") == "ckpt/reshard"]
        assert reshard, "ckpt/reshard meta event missing"
        assert reshard[0]["saved"]["mesh_shape"] == [2, 2]
        assert reshard[0]["current"]["mesh_shape"] == [4, 1]

    def test_replicated_checkpoint_loads_into_plan(self, tmp_path):
        """Legacy (no-sidecar, replicated) checkpoints restore into an
        active plan: arrays come back resharded, event emitted."""
        mesh = _mesh_2x2()
        set_mesh(mesh)
        t_rep, cfg = _tiny_trainer(mesh_shape=None)
        t_rep.cfg.logdir = str(tmp_path)
        batch = jax.tree_util.tree_map(
            np.asarray, ge._tiny_batch(2, h=64, w=64))
        t_rep.init_state(jax.random.PRNGKey(0), batch)
        path = t_rep.save_checkpoint(0, 1)
        assert not os.path.exists(path + ".partition.json")

        t_shd, _ = _tiny_trainer(mesh_shape={"data": 2, "model": 2})
        t_shd.cfg.logdir = str(tmp_path)
        t_shd.init_state(jax.random.PRNGKey(1), batch)
        assert t_shd.load_checkpoint(path, resume=True)
        mu = jax.tree_util.tree_leaves(t_shd.state["opt_G"])[1]
        spec = tuple(mu.sharding.spec)
        assert "data" in spec or "model" in spec


class TestPerDeviceBytes:
    def test_replicated_equals_global(self):
        mesh = _mesh_2x2()
        x = jax.device_put(
            np.zeros((8, 8), np.float32),
            jax.sharding.NamedSharding(mesh,
                                       jax.sharding.PartitionSpec()))
        assert per_device_tree_bytes({"x": x}) == 8 * 8 * 4

    def test_sharded_divides(self):
        mesh = _mesh_2x2()
        x = jax.device_put(
            np.zeros((8, 8), np.float32),
            jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("data", "model")))
        assert per_device_tree_bytes({"x": x}) == 8 * 8 * 4 // 4

    def test_host_arrays_count_global(self):
        assert per_device_tree_bytes(
            {"x": np.zeros((4,), np.float32)}) == 16


class TestElasticRederivation:
    """Elastic re-derivation (ISSUE 11): the same save -> re-fit ->
    restore flow the in-process resize drives, on virtual devices. A
    plan derived for the shrunken (and re-grown) world restores the
    checkpointed state redistributed under its shardings, and the
    training math stays on the never-resized trajectory."""

    def _trainer_on(self, shape, batch, seed=0, logdir=None):
        mesh = create_mesh(("data", "model"), shape,
                           devices=np.array(
                               jax.devices()[:int(np.prod(shape))]))
        set_mesh(mesh)
        trainer, cfg = _tiny_trainer(
            mesh_shape={"data": int(shape[0]), "model": int(shape[1])})
        if logdir is not None:
            trainer.cfg.logdir = str(logdir)
        trainer.init_state(jax.random.PRNGKey(seed), batch)
        return trainer, mesh

    @pytest.mark.slow
    def test_shrink_grow_roundtrip_tracks_unresized_run(self, tmp_path):
        """(4,1) -> (3,1) -> (4,1) with adam + EMA: one step per
        topology, checkpointing through each resize, stays on the
        never-resized 3-step trajectory (fp32 tolerance — the same
        global batch reduces over a different device partition at world
        3)."""
        from imaginaire_tpu.parallel.mesh import fit_mesh_shape

        batch = jax.tree_util.tree_map(
            np.asarray, ge._tiny_batch(12, h=64, w=64))

        # the never-resized reference: 3 steps on (4,1)
        t_ref, mesh = self._trainer_on((4, 1), batch)
        b = place_committed_batch(batch, mesh=mesh)
        h_ref = []
        for _ in range(3):
            d = t_ref.dis_update(b)
            g = t_ref.gen_update(b)
            h_ref.append((float(d["total"]), float(g["total"])))

        # the resized run: step on (4,1), save, re-fit to 3 devices
        t_a, mesh_a = self._trainer_on((4, 1), batch, logdir=tmp_path)
        b_a = place_committed_batch(batch, mesh=mesh_a)
        h_rsz = []
        d = t_a.dis_update(b_a)
        g = t_a.gen_update(b_a)
        h_rsz.append((float(d["total"]), float(g["total"])))
        path_a = t_a.save_checkpoint(0, 1)

        cfg41 = AttrDict(
            {"parallel": {"mesh_shape": [4, 1],
                          "axes": ["data", "model"]}})
        axes, dims = fit_mesh_shape(cfg41, 3)
        assert list(dims) == [3, 1]
        t_b, mesh_b = self._trainer_on(tuple(dims), batch, seed=1,
                                       logdir=tmp_path)
        assert t_b.load_checkpoint(path_a, resume=True)
        # the optimizer moments landed REDISTRIBUTED under the new plan
        mu = jax.tree_util.tree_leaves(t_b.state["opt_G"])[1]
        assert mu.sharding.mesh.shape["data"] == 3
        b_b = place_committed_batch(batch, mesh=mesh_b)
        d = t_b.dis_update(b_b)
        g = t_b.gen_update(b_b)
        h_rsz.append((float(d["total"]), float(g["total"])))
        path_b = t_b.save_checkpoint(0, 2)

        # grow back: re-fit to 4 devices returns the original shape
        axes, dims = fit_mesh_shape(cfg41, 4)
        assert list(dims) == [4, 1]
        t_c, mesh_c = self._trainer_on((4, 1), batch, seed=2,
                                       logdir=tmp_path)
        assert t_c.load_checkpoint(path_b, resume=True)
        mu = jax.tree_util.tree_leaves(t_c.state["opt_G"])[1]
        assert mu.sharding.mesh.shape["data"] == 4
        b_c = place_committed_batch(batch, mesh=mesh_c)
        d = t_c.dis_update(b_c)
        g = t_c.gen_update(b_c)
        h_rsz.append((float(d["total"]), float(g["total"])))

        np.testing.assert_allclose(np.asarray(h_rsz),
                                   np.asarray(h_ref), rtol=5e-3)
        for key in ("vars_G", "ema_G"):
            ref = jax.device_get(t_ref.state[key])
            rsz = jax.device_get(t_c.state[key])
            for a, b2 in zip(jax.tree_util.tree_leaves(ref),
                             jax.tree_util.tree_leaves(rsz)):
                np.testing.assert_allclose(a, b2, atol=5e-3)

    def test_model_axis_collapse_refit_restores(self, tmp_path, caplog):
        """(2,2) save -> 2 surviving devices: fit_mesh_shape collapses
        the model axis toward pure DP (warning loudly), and the
        checkpoint restores redistributed under the (2,1) plan."""
        import logging

        from imaginaire_tpu.parallel.mesh import fit_mesh_shape

        batch = jax.tree_util.tree_map(
            np.asarray, ge._tiny_batch(2, h=64, w=64))
        t_a, _ = self._trainer_on((2, 2), batch, logdir=tmp_path)
        path = t_a.save_checkpoint(0, 1)

        cfg22 = AttrDict(
            {"parallel": {"mesh_shape": [2, 2],
                          "axes": ["data", "model"]}})
        with caplog.at_level(logging.WARNING):
            axes, dims = fit_mesh_shape(cfg22, 2)
        assert list(dims) == [2, 1]
        assert any("model" in r.message for r in caplog.records)

        t_b, _ = self._trainer_on(tuple(dims), batch, seed=1,
                                  logdir=tmp_path)
        assert t_b.load_checkpoint(path, resume=True)
        a = jax.device_get(t_a.state["vars_G"]["params"])
        b = jax.device_get(t_b.state["vars_G"]["params"])
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(x, y)
        mu = jax.tree_util.tree_leaves(t_b.state["opt_G"])[1]
        assert mu.sharding.mesh.shape["data"] == 2
        assert dict(mu.sharding.mesh.shape).get("model", 1) == 1

    def test_elastic_rebind_restores_state_structure(self, tmp_path):
        """The in-process resize restore must hand optax its
        NamedTuples back: ``elastic_rebind`` drops the dead world's
        state but keeps an abstract template, and the next
        ``load_checkpoint`` restores INTO that structure — a plain
        no-target restore returns nested dicts and the first
        post-resize ``tx.update`` dies on ``state.mu``."""
        batch = jax.tree_util.tree_map(
            np.asarray, ge._tiny_batch(2, h=64, w=64))
        t, _ = self._trainer_on((2, 1), batch, logdir=tmp_path)
        structure = jax.tree_util.tree_structure(t.state)
        t.save_checkpoint(0, 1)

        t.elastic_rebind()
        assert t.state is None
        assert t._elastic_state_template is not None
        assert t.load_checkpoint(resume=True)
        assert jax.tree_util.tree_structure(t.state) == structure
        assert t._elastic_state_template is None  # donor consumed

    def test_min_shard_size_floor_across_worlds(self):
        """Re-derivation constraints at a NEW world size: the
        min_shard_size floor gates rule-axis (model) sharding, and the
        ZeRO update axis — floorless by design — still demands exact
        divisibility, so a leaf sharded over 4 hosts correctly
        replicates over 3 when divisibility is lost."""
        sizes4 = {"data": 4, "model": 1}
        sizes3 = {"data": 3, "model": 1}
        model4 = {"data": 1, "model": 4}
        # rule axis: wide kernel shards, narrow one falls below floor
        assert tuple(leaf_partition_spec(
            "kernel", (16, 128), model4,
            min_shard_size=64)) == (None, "model")
        assert tuple(leaf_partition_spec(
            "kernel", (16, 32), model4, min_shard_size=64)) == ()
        # update axis has NO width floor: a bias far below the floor
        # still shards (halving a bias is still free memory) ...
        assert tuple(leaf_partition_spec(
            "bias", (96,), sizes3, min_shard_size=64,
            update_axis="data")) == ("data",)
        # ... but exact divisibility re-applies at the new world:
        # world-4-divisible, not world-3-divisible -> replicate
        assert tuple(leaf_partition_spec(
            "bias", (128,), sizes4, min_shard_size=8,
            update_axis="data")) == ("data",)
        assert tuple(leaf_partition_spec(
            "bias", (128,), sizes3, min_shard_size=8,
            update_axis="data")) == ()
        # divisible at both worlds: stays sharded at both
        assert tuple(leaf_partition_spec(
            "bias", (96,), sizes4, min_shard_size=8,
            update_axis="data")) == ("data",)
        assert tuple(leaf_partition_spec(
            "bias", (96,), sizes3, min_shard_size=8,
            update_axis="data")) == ("data",)
